"""test_LayerGrad-parity sweep: a finite-difference gradient case for EVERY
public layer fn in ``paddle_tpu.layers.__all__``.

Reference: gserver/tests/test_LayerGrad.cpp (91 TEST blocks, one per layer
family) driven by LayerGradUtil.h:298-306 `testLayerGrad` — the reference's
core correctness oracle perturbs inputs/params per layer and compares
numeric vs analytic gradients. Here every differentiable layer gets a case;
parameter-free layers get a trainable `fc` (or `embedding` for ragged
inputs) injected UPSTREAM so the loss→fc-weight gradient flows through the
layer's VJP — a wrong backward shows up as a wrong fc gradient.

Non-differentiable / decode-only / structural layers are listed in EXEMPT
with a one-line reason each; `test_every_layer_is_covered` asserts the
CASES ∪ EXEMPT partition is exactly __all__, so a newly added layer fails
the suite until it gets a gradient case or a justified exemption.

Composite multi-layer nets are in test_layer_grad.py; this file is the
per-layer sweep.
"""

import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.layers as L
from paddle_tpu.core.lod import LoDArray

# ---------------------------------------------------------------- helpers --


def _rng():
    return np.random.RandomState(0)


def _scalar(v):
    return L.mean(L.elementwise_mul(v, v))


def _feed(name, shape, scale=0.5, dtype=np.float32):
    rng = _rng()
    if np.issubdtype(np.dtype(dtype), np.integer):
        return {name: rng.randint(0, 4, shape).astype(dtype)}
    return {name: (rng.randn(*shape) * scale).astype(dtype)}


def _pre(n=3, d=6, name="x"):
    """data [n, d] -> trainable fc(d): injects a param upstream of the
    layer under test so its VJP is exercised via the fc weight grad."""
    x = L.data(name, shape=[d])
    return L.fc(x, size=d), _feed(name, (n, d))


def _pre4(n=2, c=2, h=6, w=6, name="x"):
    """4-D [n, c, h, w] input with a trainable fc upstream."""
    x = L.data(name, shape=[c * h * w])
    hfc = L.fc(x, size=c * h * w)
    return L.reshape(hfc, (n, c, h, w)), _feed(name, (n, c * h * w))


def _pre_seq(lens=(4, 2), d=6, vocab=11, name="ids"):
    """ragged LoD rows with a trainable embedding upstream."""
    ids = L.data(name, shape=[-1], dtype=np.int32, lod_level=1,
                 append_batch_size=False)
    emb = L.embedding(ids, size=[vocab, d])
    rng = _rng()
    feed = {name: LoDArray.from_sequences(
        [rng.randint(0, vocab, (n,)).astype(np.int32) for n in lens],
        bucket=16)}
    return emb, feed


CASES = {}
TOLS = {}  # per-case overrides for (eps, rtol, atol)


def case(fn):
    CASES[fn.__name__[6:]] = fn
    return fn


def register(name, builder, tols=None):
    CASES[name] = builder
    if tols:
        TOLS[name] = tols


# ------------------------------------------------- dense unary, fc-injected -
def _unary(op, **kw):
    def build():
        h, feed = _pre()
        return _scalar(op(h, **kw)), feed
    return build


register("relu", _unary(L.relu))
register("sigmoid", _unary(L.sigmoid))
register("tanh", _unary(L.tanh))
register("softmax", _unary(L.softmax))
register("row_l2_norm", _unary(L.row_l2_norm))
register("l2_normalize", _unary(L.l2_normalize))
register("scale", _unary(L.scale, scale=1.3, bias=0.2))
register("slope_intercept", _unary(L.slope_intercept, slope=-0.7,
                                   intercept=0.3))
# clip kinks at the bounds, so the seeded fc pre-activations must keep a
# margin wider than the eps=1e-2 perturbation can close. At +-0.35 one
# element lands 1.8e-4 from the bound (central differences straddle the
# kink and read ~half the subgradient); +-0.3 leaves a 0.039 margin while
# still clipping 8 of 18 elements, so both branches stay exercised.
register("clip", _unary(L.clip, min=-0.3, max=0.3))
register("mean", _unary(L.mean))
register("sum_cost", _unary(L.sum_cost))
register("reduce_mean", _unary(L.reduce_mean, dim=1))
register("reduce_sum", _unary(L.reduce_sum, dim=0))
register("reshape", _unary(L.reshape, shape=(2, 9)))
register("transpose", _unary(L.transpose, perm=(1, 0)))
register("pad", _unary(L.pad, paddings=[1, 0, 2, 1], pad_value=0.5))
register("crop", _unary(L.crop, offsets=(1, 2), shape=(2, 3)))
register("expand", _unary(L.expand, expand_times=(2, 3)))
register("prelu", _unary(L.prelu, mode="channel"))
register("scale_shift", _unary(L.scale_shift))


@case
def build_sum_to_one_norm():
    h, feed = _pre()
    # positive rows (sigmoid) keep the normalizing denominator away from 0
    return _scalar(L.sum_to_one_norm(L.sigmoid(h))), feed


@case
def build_split():
    h, feed = _pre(3, 6)
    a, b = L.split(h, 2, dim=1)
    return _scalar(L.elementwise_sub(a, b)), feed


@case
def build_concat():
    h, feed = _pre(3, 6)
    return _scalar(L.concat([h, L.tanh(h)], axis=1)), feed


@case
def build_topk():
    # values are differentiable; k = full width keeps the loss invariant
    # under selection-order swaps so central differences see no kink when
    # a perturbation reorders near-equal elements
    h, feed = _pre(3, 6)
    vals, _ = L.topk(h, k=6)
    return _scalar(vals), feed


@case
def build_gather():
    h, feed = _pre(4, 6)
    idx = L.data("idx", shape=[3], dtype=np.int32, append_batch_size=False)
    feed["idx"] = np.array([2, 0, 3], np.int32)
    return _scalar(L.gather(h, idx)), feed


@case
def build_scatter():
    h, feed = _pre(2, 6)
    base = L.data("base", shape=[4, 6], append_batch_size=False)
    idx = L.data("idx", shape=[2], dtype=np.int32, append_batch_size=False)
    feed.update(_feed("base", (4, 6)))
    feed["idx"] = np.array([1, 3], np.int32)
    return _scalar(L.scatter(base, idx, h)), feed


@case
def build_multiplex():
    h, feed = _pre(3, 6)
    ids = L.data("ids", shape=[3], dtype=np.int32, append_batch_size=False)
    feed["ids"] = np.array([0, 1, 0], np.int32)
    return _scalar(L.multiplex([h, L.tanh(h)], ids)), feed


# ------------------------------------------------------ dense binary / misc -
@case
def build_elementwise_add():
    h, feed = _pre()
    return _scalar(L.elementwise_add(h, L.tanh(h))), feed


@case
def build_elementwise_sub():
    h, feed = _pre()
    return _scalar(L.elementwise_sub(h, L.tanh(h))), feed


@case
def build_elementwise_mul():
    h, feed = _pre()
    return _scalar(L.elementwise_mul(h, L.sigmoid(h))), feed


@case
def build_elementwise_div():
    h, feed = _pre()
    # denominator in [0.5, 1.5]: well away from 0
    den = L.scale(L.sigmoid(h), bias=0.5)
    return _scalar(L.elementwise_div(h, den)), feed


@case
def build_matmul():
    h, feed = _pre(3, 6)
    return _scalar(L.matmul(h, h, transpose_y=True)), feed


@case
def build_cos_sim():
    h, feed = _pre()
    return _scalar(L.cos_sim(h, L.tanh(h))), feed


@case
def build_dot_prod():
    h, feed = _pre()
    return _scalar(L.dot_prod(h, L.tanh(h))), feed


@case
def build_out_prod():
    h, feed = _pre(3, 4)
    return _scalar(L.out_prod(h, L.tanh(h))), feed


@case
def build_l2_distance():
    h, feed = _pre()
    return _scalar(L.l2_distance(h, L.tanh(h))), feed


@case
def build_conv_shift():
    h, feed = _pre(3, 6)
    k = L.fc(h, size=3)  # odd-width shift kernel
    return _scalar(L.conv_shift(h, k)), feed


@case
def build_interpolation():
    h, feed = _pre(3, 6)
    w = L.sigmoid(L.fc(h, size=1))
    return _scalar(L.interpolation(h, L.tanh(h), w)), feed


@case
def build_power():
    h, feed = _pre(3, 6)
    base = L.scale(L.sigmoid(h), bias=0.5)  # positive base
    w = L.sigmoid(L.fc(h, size=1))
    return _scalar(L.power(base, w)), feed


@case
def build_scaling():
    h, feed = _pre(3, 6)
    w = L.fc(h, size=1)
    return _scalar(L.scaling(h, w)), feed


@case
def build_convex_comb():
    h, feed = _pre(3, 6)
    w = L.softmax(L.fc(h, size=3))
    return _scalar(L.convex_comb(h, w)), feed


@case
def build_fc():
    x = L.data("x", shape=[5])
    h = L.fc(x, size=4, act="tanh")
    return _scalar(h), _feed("x", (3, 5))


@case
def build_bilinear_tensor_product():
    h, feed = _pre(3, 4)
    return _scalar(L.bilinear_tensor_product(h, L.tanh(h), size=2)), feed


@case
def build_factorization_machine():
    h, feed = _pre(3, 6)
    return _scalar(L.factorization_machine(h, factor_size=3)), feed


@case
def build_selective_fc():
    h, feed = _pre(3, 6)
    mask = L.data("mask", shape=[3, 4], append_batch_size=False)
    feed["mask"] = np.array([[1, 0, 1, 1]] * 3, np.float32)
    return _scalar(L.selective_fc(h, size=4, mask=mask)), feed


# ------------------------------------------------------------------- costs --
@case
def build_square_error_cost():
    h, feed = _pre(3, 4)
    lbl = L.data("lbl", shape=[4])
    feed.update(_feed("lbl", (3, 4)))
    return _scalar(L.square_error_cost(h, lbl)), feed


@case
def build_smooth_l1():
    h, feed = _pre(3, 4)
    lbl = L.data("lbl", shape=[4])
    feed.update(_feed("lbl", (3, 4)))
    return _scalar(L.smooth_l1(h, lbl)), feed


@case
def build_huber_regression_cost():
    h, feed = _pre(3, 4)
    lbl = L.data("lbl", shape=[4])
    feed.update(_feed("lbl", (3, 4)))
    return _scalar(L.huber_regression_cost(h, lbl, delta=1.0)), feed


@case
def build_huber_classification_cost():
    h, feed = _pre(3, 1)
    o = L.fc(h, size=1)
    lbl = L.data("lbl", shape=[1])
    feed["lbl"] = np.array([[1.0], [-1.0], [1.0]], np.float32)
    return _scalar(L.huber_classification_cost(o, lbl)), feed


@case
def build_binary_cross_entropy():
    h, feed = _pre(3, 4)
    p = L.sigmoid(L.fc(h, size=1))
    lbl = L.data("lbl", shape=[1])
    feed["lbl"] = np.array([[1.0], [0.0], [1.0]], np.float32)
    return _scalar(L.binary_cross_entropy(p, lbl)), feed


@case
def build_sigmoid_cross_entropy_with_logits():
    h, feed = _pre(3, 4)
    lbl = L.data("lbl", shape=[4])
    feed["lbl"] = _rng().randint(0, 2, (3, 4)).astype(np.float32)
    return _scalar(L.sigmoid_cross_entropy_with_logits(h, lbl)), feed


@case
def build_cross_entropy():
    h, feed = _pre(3, 5)
    p = L.softmax(h)
    lbl = L.data("lbl", shape=[1], dtype=np.int32)
    feed["lbl"] = np.array([[0], [3], [2]], np.int32)
    return _scalar(L.cross_entropy(p, lbl)), feed


@case
def build_cross_entropy_with_selfnorm():
    h, feed = _pre(3, 5)
    p = L.scale(L.sigmoid(h), bias=0.1)  # positive unnormalized "probs"
    lbl = L.data("lbl", shape=[1], dtype=np.int32)
    feed["lbl"] = np.array([[0], [3], [2]], np.int32)
    return _scalar(L.cross_entropy_with_selfnorm(p, lbl)), feed


@case
def build_softmax_with_cross_entropy():
    h, feed = _pre(3, 5)
    lbl = L.data("lbl", shape=[1], dtype=np.int32)
    feed["lbl"] = np.array([[0], [3], [2]], np.int32)
    return _scalar(L.softmax_with_cross_entropy(h, lbl)), feed


@case
def build_rank_cost():
    h, feed = _pre(3, 4)
    left = L.sigmoid(L.fc(h, size=1))
    right = L.sigmoid(L.fc(h, size=1))
    lbl = L.data("lbl", shape=[1])
    feed["lbl"] = np.array([[1.0], [0.0], [1.0]], np.float32)
    return _scalar(L.rank_cost(left, right, lbl)), feed


@case
def build_margin_rank_loss():
    h, feed = _pre(3, 4)
    x1 = L.fc(h, size=1)
    x2 = L.fc(h, size=1)
    lbl = L.data("lbl", shape=[1])
    feed["lbl"] = np.array([[1.0], [-1.0], [1.0]], np.float32)
    return _scalar(L.margin_rank_loss(x1, x2, lbl, margin=0.1)), feed


@case
def build_lambda_cost():
    h, feed = _pre(2, 6)
    score = L.fc(h, size=4)
    lbl = L.data("lbl", shape=[4])
    feed["lbl"] = np.array([[3.0, 2.0, 1.0, 0.0], [0.0, 1.0, 2.0, 3.0]],
                           np.float32)
    return _scalar(L.lambda_cost(score, lbl, NDCG_num=4)), feed


@case
def build_nce():
    h, feed = _pre(4, 6)
    lbl = L.data("lbl", shape=[1], dtype=np.int32)
    feed["lbl"] = np.array([[0], [3], [2], [1]], np.int32)
    cost = L.nce(h, lbl, num_classes=7, num_neg_samples=3)
    return _scalar(cost), feed


@case
def build_hsigmoid():
    h, feed = _pre(4, 6)
    lbl = L.data("lbl", shape=[1], dtype=np.int32)
    feed["lbl"] = np.array([[0], [3], [2], [1]], np.int32)
    return _scalar(L.hsigmoid(h, lbl, num_classes=7)), feed


# ------------------------------------------------------------- 4-D / conv ---
@case
def build_conv2d():
    x = L.data("x", shape=[2, 6, 6])
    h = L.conv2d(x, num_filters=3, filter_size=3, padding=1)
    return _scalar(h), _feed("x", (2, 2, 6, 6))


@case
def build_conv2d_transpose():
    x = L.data("x", shape=[2, 4, 4])
    h = L.conv2d_transpose(x, num_filters=2, filter_size=3, stride=2,
                           padding=1)
    return _scalar(h), _feed("x", (2, 2, 4, 4))


@case
def build_conv3d():
    x = L.data("x", shape=[2, 3, 4, 4])
    h = L.conv3d(x, num_filters=2, filter_size=3, padding=1)
    return _scalar(h), _feed("x", (2, 2, 3, 4, 4))


@case
def build_batch_norm():
    h4, feed = _pre4()
    return _scalar(L.batch_norm(h4)), feed


@case
def build_stacked_lstm2():
    emb, feed = _pre_seq(lens=(4, 2), d=8)
    h = L.stacked_lstm2(emb, size=8, max_len=8)
    return _scalar(L.sequence_last_step(h)), feed


@case
def build_stacked_lstm():
    # both outputs (last inter-layer fc sequence + last hidden sequence)
    # feed the loss so every weight of the stack gets a grad path
    emb, feed = _pre_seq(lens=(4, 2), d=8)
    fc_out, h = L.stacked_lstm(emb, size=8, stacked_num=2, max_len=8)
    cat = L.concat([L.sequence_last_step(fc_out),
                    L.sequence_last_step(h)], axis=1)
    return _scalar(cat), feed


@case
def build_fused_conv_bn():
    # raw-stats fused conv protocol, no-prologue unit + normalize
    x = L.data("x", shape=[4, 4, 6])
    r = L.fused_conv_bn(x, num_filters=4)
    return _scalar(L.bn_apply(r, act="relu")), _feed("x", (2, 4, 4, 6))


@case
def build_bn_stats():
    # stats-only BN feeding a fused conv's prologue (the conv2->conv3
    # seam of _bottleneck_fused)
    x = L.data("x", shape=[4, 4, 3])
    h = L.conv2d(x, num_filters=4, filter_size=3, padding=1,
                 bias_attr=False, data_format="NHWC")
    s = L.bn_stats(h)
    r = L.fused_conv_bn(s, num_filters=4, prologue_act="relu")
    return _scalar(L.bn_apply(r)), _feed("x", (2, 4, 4, 3))


@case
def build_bn_apply():
    x = L.data("x", shape=[4, 4, 3])
    h = L.conv2d(x, num_filters=4, filter_size=1, bias_attr=False,
                 data_format="NHWC")
    s = L.bn_stats(h)
    return _scalar(L.bn_apply(s, act="relu")), _feed("x", (2, 4, 4, 3))


@case
def build_layer_norm():
    h, feed = _pre(3, 8)
    return _scalar(L.layer_norm(h)), feed


@case
def build_pool2d():
    h4, feed = _pre4()
    return _scalar(L.pool2d(h4, pool_size=2, pool_type="max")), feed


@case
def build_pool3d():
    x = L.data("x", shape=[2, 3, 4, 4])
    h = L.conv3d(x, num_filters=2, filter_size=1)
    return (_scalar(L.pool3d(h, pool_size=2, pool_type="avg")),
            _feed("x", (2, 2, 3, 4, 4)))


@case
def build_maxout():
    h4, feed = _pre4(2, 4, 4, 4)
    return _scalar(L.maxout(h4, groups=2)), feed


@case
def build_lrn():
    h4, feed = _pre4(2, 4, 4, 4)
    return _scalar(L.lrn(h4, n=3)), feed


@case
def build_rotate():
    h4, feed = _pre4(2, 2, 3, 4)
    return _scalar(L.rotate(h4)), feed


@case
def build_switch_order():
    h4, feed = _pre4(2, 2, 3, 4)
    return _scalar(L.switch_order(h4)), feed


@case
def build_bilinear_interp():
    h4, feed = _pre4(2, 2, 4, 4)
    return _scalar(L.bilinear_interp(h4, out_h=7, out_w=7)), feed


@case
def build_im2sequence():
    h4, feed = _pre4(2, 2, 5, 5)
    return _scalar(L.im2sequence(h4, block_y=2, block_x=2, stride_y=1,
                                 stride_x=1)), feed


@case
def build_spp():
    h4, feed = _pre4(1, 2, 6, 6)
    return _scalar(L.spp(h4, pyramid_height=2, pool_type="avg")), feed


@case
def build_roi_pool():
    h4, feed = _pre4(1, 2, 8, 8)
    rois = L.data("rois", shape=[2, 5], append_batch_size=False)
    feed["rois"] = np.array([[0, 0, 0, 5, 5], [0, 2, 2, 7, 7]], np.float32)
    return _scalar(L.roi_pool(h4, rois, pooled_height=2, pooled_width=2)), feed


@case
def build_scale_sub_region():
    h4, feed = _pre4(2, 2, 4, 4)
    # indices: [c0, c1, h0, h1, w0, w1] 1-based inclusive region
    return _scalar(L.scale_sub_region(h4, [1, 1, 2, 3, 2, 3], scale=2.0)), feed


@case
def build_multibox_loss():
    # grads flow to the loc/conf heads (fc weights) through matching+NLL
    k = 4
    feat = L.data("feat", shape=[8])
    priors = L.data("priors", shape=[4], append_batch_size=True)
    pvar = L.data("pvar", shape=[4], append_batch_size=True)
    gt = L.data("gt", shape=[1, 4])
    gtl = L.data("gtl", shape=[1], dtype=np.int32)
    locp = L.fc(feat, size=k * 4)
    confp = L.fc(feat, size=k * 3)
    loss = L.multibox_loss(locp, confp, priors, pvar, gt, gtl,
                           overlap_threshold=0.3)
    feed = _feed("feat", (1, 8))
    feed["priors"] = np.array(
        [[0.1, 0.1, 0.4, 0.4], [0.5, 0.5, 0.9, 0.9],
         [0.2, 0.2, 0.6, 0.6], [0.6, 0.1, 0.9, 0.4]], np.float32)
    feed["pvar"] = np.full((4, 4), 0.1, np.float32)
    feed["gt"] = np.array([[[0.12, 0.1, 0.42, 0.4]]], np.float32)
    feed["gtl"] = np.array([[1]], np.int32)
    return _scalar(loss), feed


# ------------------------------------------------------------- embeddings ---
@case
def build_embedding():
    emb, feed = _pre_seq()
    return _scalar(L.sequence_pool(emb, "sum")), feed


# -------------------------------------------------------- sequence family ---
@case
def build_sequence_pool():
    emb, feed = _pre_seq()
    return _scalar(L.sequence_pool(emb, "average")), feed


@case
def build_sequence_first_step():
    emb, feed = _pre_seq()
    return _scalar(L.sequence_first_step(emb)), feed


@case
def build_sequence_last_step():
    emb, feed = _pre_seq()
    return _scalar(L.sequence_last_step(emb)), feed


@case
def build_sequence_reverse():
    emb, feed = _pre_seq()
    rev = L.sequence_reverse(emb)
    return _scalar(L.sequence_pool(rev, "first")), feed


@case
def build_sequence_reshape():
    emb, feed = _pre_seq(lens=(4, 2), d=6)
    r = L.sequence_reshape(emb, new_dim=3)
    return _scalar(L.sequence_pool(r, "sum")), feed


@case
def build_sequence_concat():
    emb, feed = _pre_seq()
    cat = L.sequence_concat([emb, L.tanh(emb)])
    return _scalar(L.sequence_pool(cat, "sum")), feed


@case
def build_sequence_conv():
    emb, feed = _pre_seq()
    h = L.sequence_conv(emb, num_filters=4, filter_size=3)
    return _scalar(L.sequence_pool(h, "sum")), feed


@case
def build_sequence_expand():
    emb, feed = _pre_seq(lens=(3, 2))
    per_seq = L.sequence_pool(emb, "average")  # dense [2, d]
    exp = L.sequence_expand(per_seq, emb)
    return _scalar(L.sequence_pool(exp, "sum")), feed


@case
def build_sequence_slice():
    emb, feed = _pre_seq(lens=(4, 3))
    off = L.data("off", shape=[2], dtype=np.int32, append_batch_size=False)
    ln = L.data("ln", shape=[2], dtype=np.int32, append_batch_size=False)
    feed["off"] = np.array([1, 0], np.int32)
    feed["ln"] = np.array([2, 2], np.int32)
    s = L.sequence_slice(emb, off, ln)
    return _scalar(L.sequence_pool(s, "sum")), feed


@case
def build_sequence_softmax():
    emb, feed = _pre_seq(lens=(4, 2), d=6)
    scores = L.fc(emb, size=1)
    sm = L.sequence_softmax(scores)
    return _scalar(L.sequence_pool(sm, "first")), feed


@case
def build_featmap_expand():
    emb, feed = _pre_seq(lens=(2, 1), d=4)
    e = L.featmap_expand(emb, num_filters=3)
    return _scalar(L.sequence_pool(e, "sum")), feed


@case
def build_row_conv():
    emb, feed = _pre_seq()
    h = L.row_conv(emb, future_context_size=2)
    return _scalar(L.sequence_pool(h, "sum")), feed


@case
def build_sub_nested_seq():
    ids = L.data("ids", shape=[-1], dtype=np.int32, lod_level=2,
                 append_batch_size=False)
    emb = L.embedding(ids, size=[9, 4])
    sel = L.data("sel", shape=[2], dtype=np.int32, append_batch_size=False)
    sub = L.sub_nested_seq(emb, sel)
    rng = _rng()
    nested = [[rng.randint(0, 9, (2,)).astype(np.int32),
               rng.randint(0, 9, (1,)).astype(np.int32)],
              [rng.randint(0, 9, (3,)).astype(np.int32)]]
    feed = {"ids": LoDArray.from_nested_sequences(nested, bucket=8),
            "sel": np.array([2, 0], np.int32)}
    return _scalar(L.sequence_pool(sub, "sum")), feed


# --------------------------------------------------------------- recurrent --
@case
def build_dynamic_lstm():
    emb, feed = _pre_seq(lens=(4, 2), d=8)
    h = L.dynamic_lstm(emb, size=8, max_len=8)
    return _scalar(L.sequence_last_step(h)), feed


@case
def build_dynamic_lstm_peepholes():
    emb, feed = _pre_seq(lens=(4, 2), d=8)
    h = L.dynamic_lstm(emb, size=8, use_peepholes=True, max_len=8)
    return _scalar(L.sequence_last_step(h)), feed


@case
def build_dynamic_gru():
    # fluid convention: dynamic_gru input is the pre-projected gates [.., 3D]
    emb, feed = _pre_seq(lens=(4, 2), d=18)
    h = L.dynamic_gru(emb, size=6, max_len=8)
    return _scalar(L.sequence_pool(h, "sum")), feed


@case
def build_simple_rnn():
    emb, feed = _pre_seq(lens=(3, 2), d=5)
    h = L.simple_rnn(emb, size=5, max_len=8)
    return _scalar(L.sequence_pool(h, "sum")), feed


@case
def build_recurrent_group():
    emb, feed = _pre_seq(lens=(3, 2), d=4)

    def step(x_t, rnn):
        h_prev = rnn.memory(shape=[4])
        h = L.fc(L.concat([x_t, h_prev], axis=1), size=4, act="tanh")
        rnn.update_memory(h_prev, h)
        return h

    out = L.recurrent_group(step, [emb], max_len=8)
    return _scalar(L.sequence_pool(out, "sum")), feed


@case
def build_RecurrentGroup():
    emb, feed = _pre_seq(lens=(3, 2), d=4)
    rnn = L.RecurrentGroup(max_len=8)
    with rnn.step():
        x_t = rnn.step_input(emb)
        h_prev = rnn.memory(shape=[4])
        h = L.fc(L.concat([x_t, h_prev], axis=1), size=4, act="tanh")
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    return _scalar(L.sequence_pool(rnn(), "sum")), feed


@case
def build_StaticRNN():
    # StaticRNN is the fluid name for the same ragged-step machinery;
    # exercise the reverse-direction variant here
    emb, feed = _pre_seq(lens=(3, 2), d=4)
    rnn = L.StaticRNN(is_reverse=True, max_len=8)
    with rnn.step():
        x_t = rnn.step_input(emb)
        h_prev = rnn.memory(shape=[3])
        h = L.fc(L.concat([x_t, h_prev], axis=1), size=3, act="tanh")
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    return _scalar(L.sequence_pool(rnn(), "sum")), feed


@case
def build_NestedRecurrentGroup():
    ids = L.data("ids", shape=[-1], dtype=np.int32, lod_level=2,
                 append_batch_size=False)
    emb = L.embedding(ids, size=[9, 4])
    outer = L.NestedRecurrentGroup(max_subseqs=3, max_sublen=4)
    with outer.step():
        sub, sub_mask = outer.step_input(emb)  # [B, L, D], [B, L]
        m = L.cast(sub_mask, np.float32)
        summed = L.reduce_sum(L.elementwise_mul(sub, m, axis=0), dim=1)
        cnt = L.clip(L.reduce_sum(m, dim=1), 1.0, 1e9)
        pooled = L.elementwise_div(summed, cnt, axis=0)
        s_prev = outer.memory(shape=[4])
        s = L.fc(L.concat([pooled, s_prev], axis=1), size=4, act="tanh")
        outer.update_memory(s_prev, s)
        outer.step_output(s)
    out = outer()
    rng = _rng()
    nested = [[rng.randint(0, 9, (2,)).astype(np.int32),
               rng.randint(0, 9, (3,)).astype(np.int32)],
              [rng.randint(0, 9, (1,)).astype(np.int32)]]
    feed = {"ids": LoDArray.from_nested_sequences(nested, bucket=16)}
    return _scalar(L.sequence_pool(out, "sum")), feed


# -------------------------------------------------------- structured costs --
@case
def build_linear_chain_crf():
    emb, feed = _pre_seq(lens=(4, 3), d=6, vocab=9)
    emit = L.fc(emb, size=4)
    lbl = L.data("lbl", shape=[-1], dtype=np.int32, lod_level=1,
                 append_batch_size=False)
    rng = _rng()
    feed["lbl"] = LoDArray.from_sequences(
        [rng.randint(0, 4, (4,)).astype(np.int32),
         rng.randint(0, 4, (3,)).astype(np.int32)], bucket=16)
    nll = L.linear_chain_crf(emit, lbl, max_len=8)
    return _scalar(nll), feed


@case
def build_warpctc():
    emb, feed = _pre_seq(lens=(6, 4), d=6, vocab=9)
    logits = L.fc(emb, size=5)
    lbl = L.data("lbl", shape=[-1], dtype=np.int32, lod_level=1,
                 append_batch_size=False)
    rng = _rng()
    feed["lbl"] = LoDArray.from_sequences(
        [rng.randint(1, 5, (2,)).astype(np.int32),
         rng.randint(1, 5, (2,)).astype(np.int32)], bucket=8)
    loss = L.warpctc(logits, lbl, blank=0, max_len=8, max_label_len=4)
    return _scalar(loss), feed


# --------------------------------------------------------------- attention --
@case
def build_multi_head_attention():
    x = L.data("x", shape=[4, 8], append_batch_size=False)
    q = L.fc(x, size=8)
    q3 = L.reshape(q, (1, 4, 8))
    h = L.multi_head_attention(q3, num_heads=2, causal=True)
    return _scalar(h), _feed("x", (4, 8))


@case
def build_attention_gru_decoder():
    src, feed = _pre_seq(lens=(4, 3), d=15, vocab=9, name="src")
    enc = L.dynamic_gru(src, size=5, max_len=8)  # input 3*size wide
    boot = L.sequence_last_step(enc)
    trg, feed2 = _pre_seq(lens=(3, 2), d=6, vocab=9, name="trg")
    feed.update(feed2)
    dec = L.attention_gru_decoder(enc, trg, boot, size=5, src_max_len=8,
                                  trg_max_len=8)
    return _scalar(L.sequence_pool(dec, "sum")), feed


# ------------------------------------------------------------ control flow --
@case
def build_cond():
    h, feed = _pre(3, 6)
    pred = L.less_than(L.mean(h), L.fill_constant([], np.float32, 10.0))
    out = L.cond(pred, lambda: L.tanh(h), lambda: L.sigmoid(h))
    return _scalar(out), feed


# ------------------------------------------------------------------ exempt --
EXEMPT = {
    # graph construction / constants — nothing differentiable
    "data": "graph input declaration, not a computation",
    "fill_constant": "constant source; no upstream parameters",
    # integer / boolean outputs: zero or undefined gradient by construction
    "accuracy": "metric with integer comparisons; not a training signal",
    "argmax": "integer index output",
    "cast": "int casts non-differentiable; float casts are identity-grad, exercised throughout by the AMP suite",
    "equal": "boolean output",
    "not_equal": "boolean output",
    "greater_equal": "boolean output",
    "greater_than": "boolean output",
    "less_equal": "boolean output",
    "less_than": "boolean output",
    "logical_and": "boolean output",
    "logical_not": "boolean output",
    "one_hot": "integer input; output constant w.r.t. every parameter",
    "eos_id": "integer mask output (decode helper)",
    "kmax_seq_score": "integer index output",
    "sampling_id": "stochastic integer sample",
    "ctc_greedy_decoder": "decode-only: integer label path output",
    "crf_decoding": "decode-only: integer viterbi path output",
    "detection_output": "decode-only: NMS box selection, integer/threshold logic",
    "BeamSearchDecoder": "decode-only generation driver (no training loss)",
    "attention_gru_beam_search": "decode-only generation driver",
    # the reusable decode-step surface (continuous-batching serving PR):
    # inference-only plumbing re-exported through layers.generation
    "GenSpec": "static op-description NamedTuple, not a computation",
    "DecodeState": "decode-slot state pytree (inference-only carrier)",
    "beam_step": "decode-only: one beam-search step over frozen weights",
    "find_generation_op": "program introspection helper, no computation",
    "gen_spec_from_op": "program introspection helper, no computation",
    "RawConvBN": "container type of the fused conv+BN protocol, not a "
                 "layer fn (its three producers/consumers have cases)",
    "prior_box": "constant anchor generation from static shapes",
    "num_priors": "python-side shape helper returning an int",
    "dropout": "stochastic mask (identity at is_test); moments covered by the oracle tests",
    "increment": "counter update on non-trainable state",
    "While": "boolean-condition loop scaffold; differentiable loops are covered by the StaticRNN/RecurrentGroup cases",
}


# ------------------------------------------------------------------- tests --
def test_every_layer_is_covered():
    """Every public layer fn has a gradient case or a justified exemption
    (and no stale entries) — the sweep can't silently fall behind
    layers/__all__ the way the 10-case round-2 sweep did."""
    public = set(L.__all__)
    # extra config variants of an already-covered layer (e.g. peepholes)
    variants = {n for n in CASES if n not in public
                and any(n.startswith(p + "_") for p in public)}
    covered = set(CASES) | set(EXEMPT)
    missing = sorted(public - covered)
    stale = sorted(covered - public - variants)
    overlap = sorted(set(CASES) & set(EXEMPT))
    assert not missing, f"layers without a gradient case or exemption: {missing}"
    assert not stale, f"sweep entries not in layers.__all__: {stale}"
    assert not overlap, f"layers both tested and exempted: {overlap}"


@pytest.mark.parametrize("name", sorted(CASES))
def test_layer_grad_sweep(name):
    pt.reset()
    pt.default_startup_program().random_seed = 3
    loss, feed = CASES[name]()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    eps, rtol, atol = TOLS.get(name, (1e-2, 5e-2, 2e-3))
    diffs = pt.check_gradient(loss, feed, eps=eps, rtol=rtol, atol=atol,
                              max_elements=4)
    assert diffs, f"{name}: no parameters checked"
