"""Transformer LM tests (CPU; attention falls back to the jnp reference).

The model family is beyond the 2017 reference (SURVEY §2.3 marks
TP/SP/attention as the modern seam); it exists to exercise the
long-context path end-to-end: flash-attention dispatcher inside the
Program IR, pre-LN blocks, gelu FFN, AMP, and training.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import models


def _build(amp=False, B=8, T=16, vocab=32):
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        toks = pt.layers.data("toks", shape=[T], dtype=np.int32)
        labels = pt.layers.data("labels", shape=[T, 1], dtype=np.int32)
        logits = models.transformer_lm(
            toks, vocab_size=vocab, dim=32, num_heads=4, num_layers=2,
            max_len=32,
        )
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, labels)
        )
        pt.optimizer.Adam(learning_rate=3e-3).minimize(loss)
    if amp:
        prog.set_amp("bfloat16")
    return prog, startup, loss


@pytest.mark.parametrize("amp", [False, True])
def test_transformer_lm_overfits_fixed_batch(amp):
    pt.reset()
    prog, startup, loss = _build(amp=amp)
    prog.random_seed = startup.random_seed = 7
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    toks = rng.randint(0, 32, (8, 16)).astype(np.int32)
    # causal LM: predict the next token
    labels = np.concatenate(
        [toks[:, 1:], np.zeros((8, 1), np.int32)], axis=1
    )[..., None]
    ls = []
    for _ in range(60 if not amp else 40):
        (l,) = exe.run(prog, feed={"toks": toks, "labels": labels},
                       fetch_list=[loss])
        ls.append(float(l))
    assert np.isfinite(ls[-1])
    assert ls[-1] < ls[0] * 0.5, (ls[0], ls[-1])


def test_transformer_causality():
    """Changing a future token must not affect earlier positions' logits
    (the causal mask through the flash dispatcher's reference path)."""
    pt.reset()
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        toks = pt.layers.data("toks", shape=[8], dtype=np.int32)
        logits = models.transformer_lm(
            toks, vocab_size=16, dim=16, num_heads=2, num_layers=1,
            max_len=8, is_test=True,
        )
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(1)
    a = rng.randint(0, 16, (2, 8)).astype(np.int32)
    b = a.copy()
    b[:, -1] = (b[:, -1] + 1) % 16  # perturb only the LAST token
    (la,) = exe.run(prog, feed={"toks": a}, fetch_list=[logits.name])
    (lb,) = exe.run(prog, feed={"toks": b}, fetch_list=[logits.name])
    np.testing.assert_allclose(la[:, :-1], lb[:, :-1], rtol=1e-5, atol=1e-5)
    assert not np.allclose(la[:, -1], lb[:, -1])


def test_transformer_trains_on_mesh_dp_mp():
    """The modern model family composes with the parallelism stack: batch
    over dp, the FFN weights Megatron-sharded over mp via Variable
    .sharding, ZeRO-sharded optimizer state — numerically equal to the
    single-device run."""
    import jax
    from jax.sharding import PartitionSpec

    from paddle_tpu import parallel as pp

    assert len(jax.devices()) == 8

    def run(parallel):
        pt.reset()
        prog, startup, loss = _build(B=8, T=16)
        prog.random_seed = startup.random_seed = 13
        if parallel:
            gb = prog.global_block()
            for i in range(2):
                gb.var(f"tfm.h{i}.ffn_in").sharding = PartitionSpec(None, "mp")
                gb.var(f"tfm.h{i}.ffn_out").sharding = PartitionSpec("mp", None)
            mesh = pp.make_mesh((4, 2), ("dp", "mp"))
            exe = pp.ParallelExecutor(mesh, shard_optimizer_state=True)
        else:
            exe = pt.Executor()
        pt.Executor().run(startup)
        rng = np.random.RandomState(2)
        toks = rng.randint(0, 32, (8, 16)).astype(np.int32)
        labels = np.concatenate(
            [toks[:, 1:], np.zeros((8, 1), np.int32)], axis=1)[..., None]
        ls = []
        for _ in range(4):
            (l,) = exe.run(prog, feed={"toks": toks, "labels": labels},
                           fetch_list=[loss])
            ls.append(float(l))
        return ls

    ref = run(parallel=False)
    par = run(parallel=True)
    np.testing.assert_allclose(par, ref, rtol=1e-4, atol=1e-5)
    assert par[-1] < par[0]


def test_transformer_rejects_overlong_sequence():
    pt.reset()
    with pt.program_guard(pt.Program(), pt.Program()):
        toks = pt.layers.data("toks", shape=[64], dtype=np.int32)
        with pytest.raises(ValueError, match="max_len"):
            models.transformer_lm(toks, vocab_size=16, dim=16, num_heads=2,
                                  num_layers=1, max_len=32)
