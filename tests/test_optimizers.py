"""Optimizer library tests.

Reference analogues: fluid tests test_sgd_op/test_adam_op/... (op_test.py
numeric checks) and Gen-1 parameter/tests. Each optimizer is checked
against a hand-computed reference step; schedules/clip/regularizers are
checked end-to-end through minimize().
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer as opt
from paddle_tpu import regularizer


def _one_step(optimizer, lr_feed_steps=1):
    """Build y = w·x, take one (or more) sgd-family steps, return w history."""
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1, param_attr=pt.ParamAttr(name="w"), bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    optimizer.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    rng = np.random.RandomState(0)
    xv = rng.randn(8, 4).astype(np.float32)
    yv = rng.randn(8, 1).astype(np.float32)
    ws = [np.asarray(scope.get("w")).copy()]
    for _ in range(lr_feed_steps):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        ws.append(np.asarray(scope.get("w")).copy())
    grad_fn = lambda w: (2.0 / 8) * xv.T @ (xv @ w - yv)
    return ws, grad_fn


def test_sgd_step_exact():
    ws, grad_fn = _one_step(opt.SGD(learning_rate=0.1))
    np.testing.assert_allclose(ws[1], ws[0] - 0.1 * grad_fn(ws[0]), rtol=1e-5, atol=1e-6)


def test_momentum_step_exact():
    ws, grad_fn = _one_step(opt.Momentum(learning_rate=0.1, momentum=0.9), 2)
    g0 = grad_fn(ws[0])
    v1 = g0
    np.testing.assert_allclose(ws[1], ws[0] - 0.1 * v1, rtol=1e-5, atol=1e-6)
    g1 = grad_fn(ws[1])
    v2 = 0.9 * v1 + g1
    np.testing.assert_allclose(ws[2], ws[1] - 0.1 * v2, rtol=1e-5, atol=1e-6)


def test_adam_step_exact():
    ws, grad_fn = _one_step(opt.Adam(learning_rate=0.1))
    g = grad_fn(ws[0])
    m = 0.1 * g
    v = 0.001 * np.square(g)
    lr_t = 0.1 * np.sqrt(1 - 0.999) / (1 - 0.9)
    expect = ws[0] - lr_t * m / (np.sqrt(v) + 1e-8)
    np.testing.assert_allclose(ws[1], expect, rtol=1e-4, atol=1e-6)


def test_adagrad_step_exact():
    ws, grad_fn = _one_step(opt.Adagrad(learning_rate=0.1))
    g = grad_fn(ws[0])
    expect = ws[0] - 0.1 * g / (np.sqrt(np.square(g)) + 1e-6)
    np.testing.assert_allclose(ws[1], expect, rtol=1e-4, atol=1e-6)


@pytest.mark.parametrize(
    "maker",
    [
        lambda: opt.Adadelta(),
        lambda: opt.RMSProp(learning_rate=0.01),
        lambda: opt.DecayedAdagrad(learning_rate=0.01),
        lambda: opt.Adamax(learning_rate=0.01),
        lambda: opt.Ftrl(learning_rate=0.1),
    ],
)
def test_all_optimizers_reduce_loss(maker):
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1, bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    maker().minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(1)
    xv = rng.randn(16, 4).astype(np.float32)
    w = np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32)
    yv = xv @ w
    first = last = None
    for i in range(60):
        (l,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        first = first if first is not None else float(l)
        last = float(l)
    assert last < first, f"{first} -> {last}"


def test_lr_schedule_exponential():
    sched = opt.ExponentialDecay(decay_steps=10, decay_rate=0.5)
    sgd = opt.SGD(learning_rate=0.1, lr_schedule=sched)
    x = pt.layers.data("x", shape=[2])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1, bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    sgd.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.ones((2, 2), np.float32)
    yv = np.ones((2, 1), np.float32)
    for _ in range(5):
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    step = float(np.asarray(pt.global_scope().get(f"{sgd.name}.step")))
    assert step == 5.0


def test_global_norm_clip_bounds_update():
    clip = opt.GradientClipByGlobalNorm(clip_norm=1e-3)
    sgd = opt.SGD(learning_rate=1.0, grad_clip=clip)
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1, param_attr=pt.ParamAttr(name="wc"), bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    sgd.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    w0 = np.asarray(scope.get("wc")).copy()
    xv = 100 * np.ones((4, 4), np.float32)
    yv = -100 * np.ones((4, 1), np.float32)
    exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    w1 = np.asarray(scope.get("wc"))
    # update magnitude == lr * clipped grad norm <= 1e-3
    assert np.linalg.norm(w1 - w0) <= 1e-3 + 1e-6


def test_l2_regularizer_shrinks_weights():
    reg = regularizer.L2Decay(0.5)
    sgd = opt.SGD(learning_rate=0.1, regularization=reg)
    x = pt.layers.data("x", shape=[2])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1, param_attr=pt.ParamAttr(name="wr"), bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    sgd.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    w0 = np.asarray(scope.get("wr")).copy()
    # zero data gradient -> pure decay: w1 = w0 - lr*coeff*w0
    xv = np.zeros((2, 2), np.float32)
    yv = np.zeros((2, 1), np.float32)
    exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    w1 = np.asarray(scope.get("wr"))
    np.testing.assert_allclose(w1, w0 * (1 - 0.1 * 0.5), rtol=1e-5)


def test_model_average_apply_restore():
    x = pt.layers.data("x", shape=[2])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1, param_attr=pt.ParamAttr(name="wa"), bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    opt.SGD(learning_rate=0.1).minimize(loss)
    avg = opt.ModelAverage(min_average_window=2, max_average_window=100)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    rng = np.random.RandomState(2)
    for _ in range(6):
        xv = rng.randn(4, 2).astype(np.float32)
        yv = rng.randn(4, 1).astype(np.float32)
        exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
    w_train = np.asarray(scope.get("wa")).copy()
    avg.apply(exe)
    w_avg = np.asarray(scope.get("wa")).copy()
    assert not np.allclose(w_train, w_avg)
    avg.restore(exe)
    np.testing.assert_allclose(np.asarray(scope.get("wa")), w_train)
