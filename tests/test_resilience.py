"""paddle_tpu.resilience: fault injection, checkpoint hardening,
StepGuard, preemption, RetryReader, circuit breaker.

The contract under test (ISSUE 4 acceptance): every fault point fires
deterministically under seeded injection and is a zero-overhead no-op
when disarmed; a torn/corrupt checkpoint — even one whose meta marker
is present — costs one checkpoint interval (quarantine + fall back to
the newest valid serial), never the run. The subprocess chaos e2e
(SIGKILL + corruption + resume → bit-identical params) lives in
test_chaos.py.
"""

import os
import signal
import socket
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu.resilience import (
    CircuitBreaker,
    CircuitOpenError,
    InjectedFault,
    NonFiniteError,
    PreemptedError,
    RetryExhausted,
    RetryReader,
    StepGuard,
    faults,
)
from paddle_tpu.resilience.breaker import CLOSED, HALF_OPEN, OPEN


# ------------------------------------------------------------- fault registry


@pytest.mark.chaos
def test_fault_hit_fires_deterministically():
    faults.arm("executor.step", hit=3)
    assert faults.fire("executor.step") is None
    assert faults.fire("executor.step") is None
    with pytest.raises(InjectedFault, match="executor.step.*hit 3"):
        faults.fire("executor.step")
    # one-shot: later hits pass again
    assert faults.fire("executor.step") is None
    st = faults.stats()["executor.step"]
    assert st["hits"] == 4 and st["fired"] == 1 and st["armed"]


@pytest.mark.chaos
def test_fault_seeded_probability_is_reproducible():
    def pattern():
        faults.reset()
        faults.arm("reader.next", p=0.5, seed=11)
        out = []
        for _ in range(20):
            try:
                faults.fire("reader.next")
                out.append(0)
            except InjectedFault:
                out.append(1)
        return out

    a, b = pattern(), pattern()
    assert a == b and sum(a) > 0, a
    faults.reset()


@pytest.mark.chaos
def test_fault_times_caps_probability_fires():
    faults.arm("reader.next", p=1.0, times=2)
    fired = 0
    for _ in range(5):
        try:
            faults.fire("reader.next")
        except InjectedFault:
            fired += 1
    assert fired == 2


def test_fault_disarmed_is_noop():
    assert not faults.is_armed()
    assert faults.fire("executor.step") is None
    # no accounting either: the disarmed fast path touches nothing
    assert faults.stats() == {}


@pytest.mark.chaos
def test_fault_spec_string_round_trip():
    faults.arm_from_spec(
        "ckpt.write:hit=2:action=corrupt; serving.predict:p=0.25:seed=3")
    assert faults.is_armed("ckpt.write")
    assert faults.is_armed("serving.predict")
    assert faults.fire("ckpt.write") is None
    assert faults.fire("ckpt.write") == "corrupt"


def test_fault_bad_specs_rejected():
    with pytest.raises(ValueError, match="unknown fault point"):
        faults.arm("ckpt.wrote", hit=1)
    with pytest.raises(ValueError, match="exactly one"):
        faults.arm("ckpt.write")
    with pytest.raises(ValueError, match="exactly one"):
        faults.arm("ckpt.write", hit=1, p=0.5)
    with pytest.raises(ValueError, match="action"):
        faults.arm("ckpt.write", hit=1, action="explode")
    with pytest.raises(ValueError, match="1-based"):
        faults.arm("ckpt.write", hit=0)


# -------------------------------------------------------- checkpoint harden


def _build_regression():
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    return loss


def _feed(seed=0, n=8, nan=False):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, 4).astype(np.float32)
    if nan:
        xs[0, 0] = np.nan
    return {"x": xs, "y": xs.sum(1, keepdims=True).astype(np.float32)}


def _two_checkpoints(d):
    """Train a step, checkpoint, train, checkpoint → serials 0 and 1."""
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    exe.run(feed=_feed(0), fetch_list=[loss])
    pio.save_checkpoint(d, {"step": 1})
    exe.run(feed=_feed(1), fetch_list=[loss])
    pio.save_checkpoint(d, {"step": 2})
    return loss


@pytest.mark.chaos
def test_truncated_newest_checkpoint_falls_back_and_quarantines(tmp_path):
    d = str(tmp_path / "ck")
    _two_checkpoints(d)
    # torn write with the meta marker present — the ISSUE io.py:354 case
    p = os.path.join(d, "checkpoint_1", pio.PARAMS_FILE)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    # hash now mismatches → quarantine + fall back to serial 0
    with pytest.warns(UserWarning, match="quarantined"):
        args = pio.load_checkpoint(d)
    assert args["step"] == 1
    assert os.path.isdir(os.path.join(d, "checkpoint_1.corrupt"))
    assert pio.get_latest_checkpoint_serial(d) == 0


@pytest.mark.chaos
def test_bitflip_detected_by_integrity_hash(tmp_path):
    d = str(tmp_path / "ck")
    _two_checkpoints(d)
    p = os.path.join(d, "checkpoint_1", pio.PARAMS_FILE)
    blob = bytearray(open(p, "rb").read())
    blob[len(blob) // 2] ^= 0xFF  # same length, one byte of rot
    open(p, "wb").write(bytes(blob))
    with pytest.raises(pio.CheckpointCorruptError, match="sha256"):
        pio.verify_checkpoint(os.path.join(d, "checkpoint_1"))
    with pytest.warns(UserWarning, match="quarantined"):
        assert pio.load_checkpoint(d)["step"] == 1


@pytest.mark.chaos
def test_get_latest_serial_verify_skips_corrupt(tmp_path):
    d = str(tmp_path / "ck")
    _two_checkpoints(d)
    p = os.path.join(d, "checkpoint_1", pio.PARAMS_FILE)
    with open(p, "r+b") as f:
        f.truncate(3)
    assert pio.get_latest_checkpoint_serial(d) == 1  # unverified view
    assert pio.get_latest_checkpoint_serial(d, verify=True) == 0
    # read-only: nothing was quarantined by the verify pass
    assert os.path.isdir(os.path.join(d, "checkpoint_1"))


@pytest.mark.chaos
def test_all_serials_corrupt_raises(tmp_path):
    d = str(tmp_path / "ck")
    _two_checkpoints(d)
    for s in (0, 1):
        p = os.path.join(d, f"checkpoint_{s}", pio.PARAMS_FILE)
        with open(p, "r+b") as f:
            f.truncate(1)
    with pytest.warns(UserWarning, match="quarantined"):
        with pytest.raises(FileNotFoundError, match="2 corrupt"):
            pio.load_checkpoint(d)


@pytest.mark.chaos
def test_injected_torn_write_with_meta_survives(tmp_path):
    """ckpt.write corrupt action: the save PUBLISHES a torn npz and the
    meta marker still lands — load must fall back, not crash."""
    d = str(tmp_path / "ck")
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    exe.run(feed=_feed(0), fetch_list=[loss])
    pio.save_checkpoint(d, {"step": 1})
    faults.arm("ckpt.write", hit=1, action="corrupt")
    pio.save_checkpoint(d, {"step": 2})
    faults.disarm()
    assert faults.stats()["ckpt.write"]["fired"] == 1
    assert os.path.exists(os.path.join(d, "checkpoint_1", pio.META_FILE))
    with pytest.warns(UserWarning, match="quarantined"):
        assert pio.load_checkpoint(d)["step"] == 1


@pytest.mark.chaos
def test_injected_meta_fault_leaves_checkpoint_invisible(tmp_path):
    """Dying between payload and meta (ckpt.meta raise) must leave the
    serial incomplete — invisible to the scan, previous one loads."""
    d = str(tmp_path / "ck")
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pio.save_checkpoint(d, {"step": 1})
    faults.arm("ckpt.meta", hit=1)
    with pytest.raises(InjectedFault):
        pio.save_checkpoint(d, {"step": 2})
    faults.disarm()
    assert pio.get_latest_checkpoint_serial(d) == 0
    assert pio.load_checkpoint(d)["step"] == 1


@pytest.mark.chaos
def test_injected_write_failure_keeps_previous_checkpoint(tmp_path):
    d = str(tmp_path / "ck")
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pio.save_checkpoint(d, {"step": 1})
    faults.arm("ckpt.write", hit=1)
    with pytest.raises(InjectedFault):
        pio.save_checkpoint(d, {"step": 2})
    faults.disarm()
    assert pio.load_checkpoint(d)["step"] == 1


def test_retention_spares_incomplete_serials(tmp_path):
    """An incomplete dir (no meta — possibly mid-write by another
    process) must never be swept; complete old serials are."""
    d = str(tmp_path / "ck")
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pio.save_checkpoint(d, {"step": 1}, max_num_checkpoints=2)
    # manufacture an in-flight (incomplete) serial 50: payload, no meta
    os.makedirs(os.path.join(d, "checkpoint_50"))
    open(os.path.join(d, "checkpoint_50", pio.PARAMS_FILE), "wb").close()
    for step in (2, 3, 4):
        pio.save_checkpoint(d, {"step": step}, max_num_checkpoints=2)
    kept = sorted(n for n in os.listdir(d) if n.startswith("checkpoint_"))
    # the new saves took serials 1..3 (allocation counts complete
    # serials only); retention kept the newest 2 complete ones and never
    # touched the incomplete 50
    assert kept == ["checkpoint_2", "checkpoint_3", "checkpoint_50"], kept


# --------------------------------------------------------------- StepGuard


def _nan_reader(nan_batches, total=10, n=8):
    def reader():
        for i in range(total):
            yield _feed(seed=i, n=n, nan=i in nan_batches)
    return reader


@pytest.mark.chaos
def test_step_guard_skips_and_rolls_back(tmp_path):
    d = str(tmp_path / "ck")
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    guard = StepGuard(max_consecutive=2, cooldown_steps=2, lr_factor=0.1)
    cc = pt.CheckpointConfig(d, epoch_interval=0, step_interval=2)
    t = pt.Trainer(loss, checkpoint_config=cc, step_guard=guard)
    # batches 4..6 are poisoned. The updates were already applied when
    # the guard sees the loss, so each NaN batch re-poisons the params:
    # rollback #1 fires after batches 4+5; batch 6 poisons again and the
    # (clean-input) batch 7 still reads NaN off the params → rollback
    # #2; batches 8-9 then run clean and end the cool-down.
    m = t.train(_nan_reader({4, 5, 6}), num_passes=1)
    assert np.isfinite(m["cost"]), m
    st = guard.stats()
    assert st["skipped"] == 4 and st["rollbacks"] == 2, st
    # parameters are finite after recovery
    w = np.asarray(pt.global_scope().get(
        pt.default_main_program().parameters()[0].name))
    assert np.isfinite(w).all()
    # cool-down ended (≥2 clean steps ran after the rollback): LR is back
    # to its base value
    lr_names = [v.name for v in pt.default_main_program().persistables()
                if v.name.endswith(".lr")]
    assert lr_names
    lr = float(np.asarray(pt.global_scope().get(lr_names[0])))
    assert lr == pytest.approx(0.05)


@pytest.mark.chaos
def test_step_guard_poisoned_checkpoint_cadence_suppressed(tmp_path):
    """A checkpoint must never be written off the back of a skipped
    (non-finite) step — the cadence counter lands on a bad step and the
    save is suppressed."""
    d = str(tmp_path / "ck")
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    guard = StepGuard(max_consecutive=1, cooldown_steps=1)
    cc = pt.CheckpointConfig(d, epoch_interval=0, step_interval=1,
                             max_num_checkpoints=100)
    t = pt.Trainer(loss, checkpoint_config=cc, step_guard=guard)
    # cadence is EVERY step; batch 2 is poisoned — the skipped step must
    # not produce a serial, and every serial that exists holds finite
    # params (the rollback restored before the next save)
    t.train(_nan_reader({2}, total=6), num_passes=1)
    assert guard.stats()["rollbacks"] == 1
    latest = pio.get_latest_checkpoint_serial(d)
    assert latest >= 2
    for s in range(latest + 1):
        sd = os.path.join(d, f"checkpoint_{s}")
        if not os.path.isdir(sd):
            continue  # swept or never written (the skipped step)
        pt.reset_global_scope()
        pio.load_vars(sd)
        for name in pt.global_scope().keys():
            assert np.isfinite(np.asarray(pt.global_scope().get(name))).all()


@pytest.mark.chaos
def test_step_guard_without_checkpoint_raises():
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    guard = StepGuard(max_consecutive=2)
    t = pt.Trainer(loss, step_guard=guard)
    with pytest.raises(NonFiniteError, match="no checkpoint"):
        t.train(_nan_reader(set(range(10))), num_passes=1)


# -------------------------------------------------------------- preemption


@pytest.mark.chaos
def test_sigterm_preempts_with_emergency_checkpoint(tmp_path):
    d = str(tmp_path / "ck")
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    cc = pt.CheckpointConfig(d, epoch_interval=0)  # NO cadence at all
    t = pt.Trainer(loss, checkpoint_config=cc)

    def preempt_at_3(e):
        if isinstance(e, pt.EndIteration) and e.step == 3:
            os.kill(os.getpid(), signal.SIGTERM)

    with pytest.raises(PreemptedError, match="SIGTERM"):
        t.train(_nan_reader(set()), num_passes=3,
                event_handler=preempt_at_3)
    # the emergency checkpoint recorded the mid-pass position
    args = pio.load_checkpoint(d)
    assert args["step"] == 3 and args["mid_pass"] and args["batch_id"] == 2
    # resume re-enters pass 0 at batch 3
    pt.reset_global_scope()
    t2 = pt.Trainer(loss, checkpoint_config=cc)
    t2.init()
    assert t2.start_pass == 0 and t2._resume_batch == 3 and t2.step == 3
    # and the original SIGTERM disposition was restored on the way out
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL


def test_preempt_exit_code_is_ex_tempfail():
    from paddle_tpu.resilience import PREEMPT_EXIT_CODE

    assert PREEMPT_EXIT_CODE == 75  # BSD sysexits EX_TEMPFAIL


_PREEMPT_CFG = '''
import os
import signal

import numpy as np

import paddle_tpu as pt


def get_model():
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)

    def reader():
        rng = np.random.RandomState(0)
        for i in range(10):
            if i == 3:  # the scheduler preempts us mid-pass
                os.kill(os.getpid(), signal.SIGTERM)
            xs = rng.randn(4, 4).astype(np.float32)
            yield {"x": xs, "y": xs.sum(1, keepdims=True)}

    return {"cost": loss, "reader": reader, "num_passes": 3}
'''


@pytest.mark.chaos
def test_cli_train_maps_preemption_to_exit_75(tmp_path, capsys):
    from paddle_tpu import cli
    from paddle_tpu.resilience import PREEMPT_EXIT_CODE

    cfg = tmp_path / "cfg.py"
    cfg.write_text(_PREEMPT_CFG)
    rc = cli.main(["train", "--config", str(cfg),
                   "--save_dir", str(tmp_path / "ck")])
    assert rc == PREEMPT_EXIT_CODE
    assert "preempted" in capsys.readouterr().out
    # the emergency checkpoint is there for the rescheduled run
    assert pio.get_latest_checkpoint_serial(str(tmp_path / "ck")) >= 0


# ------------------------------------------------------------- RetryReader


@pytest.mark.chaos
def test_retry_reader_replays_and_delivers_everything():
    faults.arm("reader.next", hit=5)  # one failure mid-stream

    def reader():
        for i in range(8):
            yield i

    rr = RetryReader(reader, base_delay_s=0.001, max_delay_s=0.002)
    assert list(rr()) == list(range(8))
    assert rr.retries == 1
    st = faults.stats()["reader.next"]
    assert st["fired"] == 1


@pytest.mark.chaos
def test_retry_reader_budget_exhausts():
    faults.arm("reader.next", p=1.0)  # every sample fails

    def reader():
        yield from range(4)

    rr = RetryReader(reader, max_retries=2, base_delay_s=0.001)
    with pytest.raises(RetryExhausted, match="budget 2"):
        list(rr())
    assert rr.retries == 3  # initial + 2 retries, all failed


@pytest.mark.chaos
def test_retry_reader_counts_into_stat_set():
    # hit numbering advances across replays (replayed samples re-fire):
    # hit 2 fails run 1, the replay covers hits 3-8, hit 7 fails it again
    faults.arm("reader.next", hits=(2, 7))
    stats = pt.profiler.StatSet()

    def reader():
        yield from range(6)

    rr = RetryReader(reader, base_delay_s=0.001, stat_set=stats)
    assert list(rr()) == list(range(6))
    s = stats.get("resilience/reader_retry")
    assert s.count == 2 and s.total > 0


def test_retry_reader_trains(tmp_path):
    """A RetryReader drops in anywhere a reader goes."""
    faults.arm("reader.next", hit=3)
    loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    t = pt.Trainer(loss)
    m = t.train(RetryReader(_nan_reader(set(), total=6),
                            base_delay_s=0.001),
                num_passes=1)
    assert np.isfinite(m["cost"]) and t.step == 6


# ---------------------------------------------------------- circuit breaker


def test_circuit_breaker_state_machine():
    clock = [0.0]
    b = CircuitBreaker(failure_threshold=3, reset_timeout_s=10.0,
                       clock=lambda: clock[0])
    assert b.state() == CLOSED and b.admit()
    for _ in range(2):
        b.record_failure()
    assert b.state() == CLOSED  # threshold is 3 CONSECUTIVE
    b.record_success()
    for _ in range(3):
        b.record_failure()
    assert b.state() == OPEN and not b.admit()
    clock[0] = 9.9
    assert not b.admit()
    clock[0] = 10.0
    assert b.state() == HALF_OPEN
    for _ in range(3):
        assert b.would_admit()  # read-only: never consumes the probe
    assert b.admit()          # the probe
    assert not b.would_admit()
    assert not b.admit()      # probe budget spent
    b.record_failure()        # probe failed → re-open, timer restarts
    assert b.state() == OPEN and not b.admit()
    clock[0] = 20.0
    assert b.admit()
    b.record_success()        # probe succeeded → closed
    assert b.state() == CLOSED and b.admit()
    assert b.stats()["opens"] == 2


class _FakeEngine:
    """Just enough surface for MicroBatcher."""

    class policy:
        max_batch_size = 8

    def __init__(self, metrics=None, fail=False, delay_s=0.0):
        from paddle_tpu.serving import MetricSet

        self.metrics = metrics or MetricSet()
        self.model_name = "fake"
        self.fail = fail
        self.delay_s = delay_s
        self.calls = 0

    def predict(self, feed):
        self.calls += 1
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise RuntimeError("engine down")
        return [feed["x"] * 2.0]


@pytest.mark.chaos
def test_batcher_breaker_opens_and_half_open_recovers():
    from paddle_tpu.serving import MicroBatcher

    clock = [0.0]
    eng = _FakeEngine(fail=True)
    b = MicroBatcher(eng, max_wait_ms=1.0,
                     breaker=CircuitBreaker(failure_threshold=2,
                                            reset_timeout_s=5.0,
                                            clock=lambda: clock[0]))
    b.start()
    try:
        feed = {"x": np.ones((1, 2), np.float32)}
        for _ in range(2):
            with pytest.raises(RuntimeError, match="engine down"):
                b.predict(feed, timeout_ms=2000)
        # circuit now open: submission fails fast, engine untouched
        calls = eng.calls
        with pytest.raises(CircuitOpenError, match="circuit open"):
            b.predict(feed, timeout_ms=2000)
        assert eng.calls == calls
        assert b.metrics.counter_value("circuit_open_total") == 1
        # heal the engine, step past the reset timeout → probe closes it
        eng.fail = False
        clock[0] = 5.0
        (out,) = b.predict(feed, timeout_ms=2000)
        np.testing.assert_array_equal(out, feed["x"] * 2.0)
        assert b.breaker.state() == CLOSED
    finally:
        b.stop()


@pytest.mark.chaos
def test_deadline_rechecked_after_engine_run():
    """A request that expires INSIDE the engine call (cold bucket
    compile) gets a clean DeadlineError, not a late 200."""
    from paddle_tpu.serving import MicroBatcher

    eng = _FakeEngine(delay_s=0.25)
    b = MicroBatcher(eng, max_wait_ms=1.0)
    b.start()
    try:
        with pytest.raises(DeadlineError, match="during the engine run"):
            b.predict({"x": np.ones((1, 2), np.float32)}, timeout_ms=60)
        assert eng.calls == 1  # it DID run — the result was just too late
        # an unexpired request straight after is served normally
        (out,) = b.predict({"x": np.ones((1, 2), np.float32)},
                           timeout_ms=5000)
        assert out.shape == (1, 2)
    finally:
        b.stop()


from paddle_tpu.serving import DeadlineError  # noqa: E402  (test helper)


@pytest.mark.chaos
def test_serving_predict_fault_point_feeds_breaker(tmp_path):
    """An armed serving.predict fault is an engine failure end to end:
    fans out to callers, trips the breaker, /healthz degrades."""
    import json
    import urllib.request

    from paddle_tpu.serving import ModelRegistry, make_server

    # build + save a tiny model
    pt.reset()
    x = pt.layers.data("x", shape=[4])
    pred = pt.layers.fc(x, size=1)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    d = str(tmp_path / "m")
    pt.io.save_inference_model(d, ["x"], [pred])

    reg = ModelRegistry()
    reg.add("m", model_dir=d,
            breaker=CircuitBreaker(failure_threshold=2, reset_timeout_s=60))
    srv = make_server(reg)
    srv.serve_background()
    try:
        url = f"http://127.0.0.1:{srv.port}"
        faults.arm("serving.predict", p=1.0)
        body = json.dumps({"inputs": {"x": [[0, 0, 0, 0]]}}).encode()
        codes = []
        for _ in range(3):
            try:
                urllib.request.urlopen(urllib.request.Request(
                    url + "/predict/m", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=30)
                codes.append(200)
            except urllib.error.HTTPError as e:
                codes.append(e.code)
        faults.disarm()
        assert codes[:2] == [500, 500] and codes[2] == 503, codes
        with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
            h = json.load(r)
        assert h["status"] == "degraded" and h["circuits"]["m"] == "open"
        with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
            m = r.read().decode()
        assert "ptserving_circuit_state_m 2" in m
        assert "ptserving_circuit_open_total" in m
    finally:
        srv.shutdown()
        reg.stop()
        srv.server_close()


# ------------------------------------------------------- download timeout


def test_download_counts_socket_timeouts(tmp_path, monkeypatch):
    from paddle_tpu.data.datasets import common

    monkeypatch.setenv("PADDLE_TPU_DATA_HOME", str(tmp_path))
    calls = []

    def stalled(url, timeout=None):
        calls.append(timeout)
        raise socket.timeout("recv stalled")

    monkeypatch.setattr("urllib.request.urlopen", stalled)
    with pytest.raises(RuntimeError, match=r"3 of them stalled past"):
        common.download("http://mirror/x.tgz", "unit", "0" * 32,
                        timeout=0.5)
    assert calls == [0.5, 0.5, 0.5]  # timeout reached urlopen, 3 tries
