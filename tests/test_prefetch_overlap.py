"""DevicePrefetcher overlap-efficiency test (tunnel-free, VERDICT r2 #7).

Reference: gserver/dataproviders/DataProvider.h:292-375 — the
double-buffered async loader exists so the trainer never waits on IO
while batches arrive faster than steps. Here the producer cost (read +
decode + h2d) is a real device_put of a ResNet-batch-sized array plus a
synthetic decode sleep, the consumer cost is a synthetic step, both on
the CPU backend — no axon tunnel in the loop — and the pipelined wall
time must approach max(producer, consumer) instead of their sum.
"""

import time

import jax
import numpy as np

from paddle_tpu.data.feeder import DevicePrefetcher

BATCH_MB = 77  # the ResNet-50 bs128 feed size the r2 bench couldn't drive


def _run(produce_sleep, step_sleep, n_batches):
    batch = np.zeros((BATCH_MB * 1024 * 1024 // 4,), np.float32)

    def reader():
        for _ in range(n_batches):
            time.sleep(produce_sleep)  # synthetic read+decode
            yield {"x": batch}  # DevicePrefetcher does the device_put

    # pipelined
    t0 = time.perf_counter()
    for feed in DevicePrefetcher(reader, depth=2):
        jax.block_until_ready(feed["x"])
        time.sleep(step_sleep)  # synthetic device step
    t_pipe = time.perf_counter() - t0

    # sequential (no overlap): same stages inline
    t0 = time.perf_counter()
    for _ in range(n_batches):
        time.sleep(produce_sleep)
        x = jax.device_put(batch)
        jax.block_until_ready(x)
        time.sleep(step_sleep)
    t_seq = time.perf_counter() - t0
    return t_pipe, t_seq


def test_overlap_hides_faster_producer():
    """Producer faster than the step → pipelined time ~= consumer time
    alone (>=90% overlap efficiency), sequential pays the sum.

    This is a wall-clock measurement on a single-core box: transient
    contention (another suite, a bench subprocess) starves the producer
    thread and tanks one reading (observed 0.59-0.85 under load,
    >=0.95 in isolation), so the measurement retries before failing
    rather than loosening the bar."""
    n = 8
    produce, step = 0.02, 0.06
    attempts = []
    for _ in range(3):
        t_pipe, t_seq = _run(produce, step, n)
        # h2d put of the 77MB batch costs some real time on CPU too;
        # bound the consumer-side ideal by sequential minus produce
        per_pipe = t_pipe / n
        per_seq = t_seq / n
        eff = (per_seq - produce) / per_pipe
        saved = per_pipe < per_seq - 0.5 * produce
        attempts.append({"eff": round(eff, 3), "saved": saved,
                         "per_pipe": round(per_pipe, 4),
                         "per_seq": round(per_seq, 4)})
        if eff >= 0.9 and saved:
            return
    raise AssertionError(
        "no attempt had BOTH overlap efficiency >= 0.9 AND an absolute "
        f"saving of >= half the produce time: {attempts}")


def test_producer_bound_degrades_gracefully():
    """Producer slower than the step → throughput tracks the producer,
    not producer+consumer."""
    n = 6
    produce, step = 0.08, 0.02
    t_pipe, t_seq = _run(produce, step, n)
    per_pipe = t_pipe / n
    per_seq = t_seq / n
    # pipelined ~= producer cost alone (within 25% slack for the h2d)
    assert per_pipe < per_seq - 0.5 * step, (per_pipe, per_seq)
