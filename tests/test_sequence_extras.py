"""Tests for the widened sequence set: slice/reshape/reverse/kmax/
sub_nested/featmap/eos/sequence_conv.

Reference analogues: gserver/tests/test_SeqSliceLayerGrad.cpp,
test_KmaxSeqScore.cpp, test_CrossEntropyOverBeamGrad.cpp fixtures and the
fluid tests test_sequence_slice_op.py / test_sequence_conv.py.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.lod import LoDArray


def _lod(seqs, dtype=np.float32, **kw):
    return LoDArray.from_sequences([np.asarray(s, dtype) for s in seqs], **kw)


def _ragged(out):
    """LoDArray result → list of per-sequence numpy arrays."""
    data = np.asarray(out.data)
    lens = np.asarray(out.lengths)
    n = int(out.num_seqs)
    offs = np.concatenate([[0], np.cumsum(lens)])
    return [data[offs[i] : offs[i + 1]] for i in range(n)]


def test_sequence_slice():
    x = pt.layers.data("x", shape=[-1, 2], lod_level=1, append_batch_size=False)
    off = pt.layers.data("off", shape=[2], dtype=np.int32, append_batch_size=False)
    ln = pt.layers.data("ln", shape=[2], dtype=np.int32, append_batch_size=False)
    y = pt.layers.sequence_slice(x, off, ln)
    exe = pt.Executor()
    seqs = [[[1, 1], [2, 2], [3, 3], [4, 4]], [[10, 10], [20, 20]]]
    (out,) = exe.run(
        feed={"x": _lod(seqs, bucket=8),
              "off": np.array([1, 0], np.int32),
              "ln": np.array([2, 1], np.int32)},
        fetch_list=[y], return_numpy=False)
    r = _ragged(out)
    np.testing.assert_allclose(r[0], [[2, 2], [3, 3]])
    np.testing.assert_allclose(r[1], [[10, 10]])


def test_sequence_reshape():
    x = pt.layers.data("x", shape=[-1, 4], lod_level=1, append_batch_size=False)
    y = pt.layers.sequence_reshape(x, new_dim=2)
    exe = pt.Executor()
    seqs = [[[1, 2, 3, 4]], [[5, 6, 7, 8], [9, 10, 11, 12]]]
    (out,) = exe.run(feed={"x": _lod(seqs, bucket=4)}, fetch_list=[y],
                     return_numpy=False)
    r = _ragged(out)
    np.testing.assert_allclose(r[0], [[1, 2], [3, 4]])
    np.testing.assert_allclose(r[1], [[5, 6], [7, 8], [9, 10], [11, 12]])


def test_sequence_reverse():
    x = pt.layers.data("x", shape=[-1, 1], lod_level=1, append_batch_size=False)
    y = pt.layers.sequence_reverse(x)
    exe = pt.Executor()
    seqs = [[[1], [2], [3]], [[4], [5]]]
    (out,) = exe.run(feed={"x": _lod(seqs, bucket=8)}, fetch_list=[y],
                     return_numpy=False)
    r = _ragged(out)
    np.testing.assert_allclose(r[0], [[3], [2], [1]])
    np.testing.assert_allclose(r[1], [[5], [4]])


def test_kmax_seq_score():
    x = pt.layers.data("x", shape=[-1, 1], lod_level=1, append_batch_size=False)
    y = pt.layers.kmax_seq_score(x, beam_size=2)
    exe = pt.Executor()
    seqs = [[[0.1], [0.9], [0.5]], [[0.7]]]
    (out,) = exe.run(feed={"x": _lod(seqs, bucket=8)}, fetch_list=[y])
    np.testing.assert_array_equal(out[0], [1, 2])  # indices within seq 0
    assert out[1][0] == 0 and out[1][1] == -1  # second slot padded


def test_sub_nested_seq():
    x = pt.layers.data("x", shape=[-1, 1], lod_level=2, append_batch_size=False)
    sel = pt.layers.data("sel", shape=[3], dtype=np.int32,
                         append_batch_size=False)
    y = pt.layers.sub_nested_seq(x, sel)
    exe = pt.Executor()
    # nested: seq0 = [[1,2],[3]], seq1 = [[4,5,6]] → global subs 0,1,2
    nested = [[[[1], [2]], [[3]]], [[[4], [5], [6]]]]
    lod = LoDArray.from_nested_sequences(
        [[np.asarray(ss, np.float32) for ss in s] for s in nested], bucket=8)
    (out,) = exe.run(
        feed={"x": lod, "sel": np.array([2, 0, -1], np.int32)},
        fetch_list=[y], return_numpy=False)
    r = _ragged(out)
    assert len(r) == 2
    np.testing.assert_allclose(r[0], [[4], [5], [6]])  # global sub 2
    np.testing.assert_allclose(r[1], [[1], [2]])  # global sub 0


def test_featmap_expand_and_eos():
    x = pt.layers.data("x", shape=[-1, 2], lod_level=1, append_batch_size=False)
    y = pt.layers.featmap_expand(x, num_filters=3)
    exe = pt.Executor()
    seqs = [[[1.0, 2.0]]]
    (out,) = exe.run(feed={"x": _lod(seqs, bucket=4)}, fetch_list=[y],
                     return_numpy=False)
    np.testing.assert_allclose(np.asarray(out.data)[0],
                               [1, 2, 1, 2, 1, 2])

    pt.reset()
    ids = pt.layers.data("ids", shape=[-1, 1], dtype=np.int32, lod_level=1,
                         append_batch_size=False)
    e = pt.layers.eos_id(ids, eos_id=2)
    exe = pt.Executor()
    lod = _lod([[[1], [2], [3]]], np.int32, bucket=4)
    (out,) = exe.run(feed={"ids": lod}, fetch_list=[e], return_numpy=False)
    np.testing.assert_allclose(np.asarray(out.data)[:3, 0], [0, 1, 0])


def test_sequence_conv_boundary_masking():
    x = pt.layers.data("x", shape=[-1, 2], lod_level=1, append_batch_size=False)
    y = pt.layers.sequence_conv(x, num_filters=2, filter_size=3,
                                bias_attr=False)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    # identity-ish filter: output = sum of context window
    wname = [v for v in pt.default_main_program().global_block().vars
             if ".w" in v][0]
    w = np.concatenate([np.eye(2), np.eye(2), np.eye(2)], axis=0).astype(
        np.float32)
    pt.global_scope().set(wname, w)
    seqs = [[[1, 1], [2, 2], [4, 4]], [[10, 10]]]
    (out,) = exe.run(feed={"x": _lod(seqs, bucket=8)}, fetch_list=[y],
                     return_numpy=False)
    r = _ragged(out)
    # token 0 of seq 0: window (pad, x0, x1) = 1+2 = 3; token 1: 1+2+4=7
    np.testing.assert_allclose(r[0], [[3, 3], [7, 7], [6, 6]])
    # seq 1 single token must not see seq 0
    np.testing.assert_allclose(r[1], [[10, 10]])


def test_sequence_conv_trains_text_classifier():
    """sequence_conv + max-pool text classifier converges (the Gen-1
    text-conv recipe from the sentiment demo)."""
    rng = np.random.RandomState(0)
    vocab, emb_d = 30, 8
    x = pt.layers.data("x", shape=[-1, 1], dtype=np.int32, lod_level=1,
                       append_batch_size=False)
    lab = pt.layers.data("lab", shape=[1], dtype=np.int32)
    emb = pt.layers.embedding(x, size=[vocab, emb_d])
    conv = pt.layers.sequence_conv(emb, num_filters=16, filter_size=3,
                                   act="relu")
    pooled = pt.layers.sequence_pool(conv, "max")
    logits = pt.layers.fc(pooled, size=2)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, lab))
    pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    def batch():
        seqs, labs = [], []
        for _ in range(16):
            y = rng.randint(0, 2)
            n = rng.randint(3, 7)
            toks = rng.randint(10 * y, 10 * y + 10, (n, 1))
            seqs.append(toks.astype(np.int32))
            labs.append([y])
        return (LoDArray.from_sequences(seqs, bucket=128),
                np.asarray(labs, np.int32))

    losses = []
    for _ in range(30):
        xv, lv = batch()
        (l,) = exe.run(feed={"x": xv, "lab": lv}, fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < 0.25, losses[-5:]


def test_sequence_slice_bucketed_max_seqs():
    x = pt.layers.data("x", shape=[-1, 1], lod_level=1, append_batch_size=False)
    off = pt.layers.data("off", shape=[2], dtype=np.int32,
                         append_batch_size=False)
    ln = pt.layers.data("ln", shape=[2], dtype=np.int32,
                        append_batch_size=False)
    y = pt.layers.sequence_slice(x, off, ln)
    exe = pt.Executor()
    seqs = [np.asarray([[1.0], [2.0], [3.0]], np.float32),
            np.asarray([[4.0], [5.0]], np.float32)]
    lod = LoDArray.from_sequences(seqs, bucket=8, max_seqs=4)  # bucketed
    (out,) = exe.run(
        feed={"x": lod, "off": np.array([1, 0], np.int32),
              "ln": np.array([1, 2], np.int32)},
        fetch_list=[y], return_numpy=False)
    r = _ragged(out)
    np.testing.assert_allclose(r[0], [[2.0]])
    np.testing.assert_allclose(r[1], [[4.0], [5.0]])
