"""Flags, stat timers, NaN guard, op-path diagnostics tests.

Reference analogues: utils/Flags.cpp gflags registry; utils/Stat.h
REGISTER_TIMER; fluid executor.cc:60-72 FLAGS_check_nan_inf;
utils/CustomStackTrace.h layer-path crash dumps.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import profiler
from paddle_tpu.flags import FLAGS, define_flag, parse_flags


def test_flag_define_parse_and_env(monkeypatch):
    define_flag("test_flag_xyz", 3, "a test flag")
    assert FLAGS.test_flag_xyz == 3
    rest = parse_flags(["--test_flag_xyz=7", "positional", "--unknown=1"])
    assert FLAGS.test_flag_xyz == 7
    assert rest == ["positional", "--unknown=1"]
    FLAGS.test_flag_xyz = "9"  # coerced to the default's type
    assert FLAGS.test_flag_xyz == 9
    monkeypatch.setenv("PT_FLAGS_TEST_ENV_FLAG", "true")
    define_flag("test_env_flag", False)
    assert FLAGS.test_env_flag is True
    with pytest.raises(AttributeError):
        FLAGS.never_defined


def test_parse_bool_flag_bare():
    """gflags semantics: bare --bool_flag sets True, never eats the next arg."""
    define_flag("test_bool_pf", False)
    rest = parse_flags(["--test_bool_pf", "train.py"])
    assert FLAGS.test_bool_pf is True
    assert rest == ["train.py"]
    define_flag("test_int_pf", 1)
    rest = parse_flags(["--test-int-pf", "5", "x"])  # hyphens normalize
    assert FLAGS.test_int_pf == 5 and rest == ["x"]


def test_parse_never_consumes_flag_as_value():
    """--int_flag --other: the next token is itself a flag, so it must not
    be eaten as the value (and no bare-ValueError crash)."""
    define_flag("test_int_nv", 2)
    define_flag("test_bool_nv", False)
    rest = parse_flags(["--test_int_nv", "--test_bool_nv"])
    assert FLAGS.test_int_nv == 2  # unvalued: left alone
    assert FLAGS.test_bool_nv is True
    assert rest == ["--test_int_nv"]


def test_parse_bad_value_names_flag():
    define_flag("test_int_bv", 2)
    with pytest.raises(ValueError, match="test_int_bv"):
        parse_flags(["--test_int_bv=notanint"])
    with pytest.raises(ValueError, match="test_int_bv"):
        parse_flags(["--test_int_bv", "notanint"])


def test_init_atomic_on_bad_value():
    """A failing coercion mid-kwargs applies nothing (docstring claim)."""
    before = FLAGS.log_period
    with pytest.raises((TypeError, ValueError)):
        pt.init(log_period=99, beam_size="xyz")  # int("xyz") fails
    assert FLAGS.log_period == before


def test_stat_timers():
    ss = profiler.StatSet()
    for _ in range(3):
        with ss.timer("step", always=True):
            pass
    with ss.timer("gated_off"):  # FLAGS.enable_timers is False
        pass
    assert ss.stats["step"].count == 3
    assert "gated_off" not in ss.stats
    table = ss.print_all_status()
    assert "step" in table and "count" in table


def test_parameter_stats():
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.fc(x, size=2)
    loss = pt.layers.mean(y)
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    exe.run(feed={"x": np.ones((2, 4), np.float32)}, fetch_list=[loss])
    stats = profiler.parameter_stats()
    assert stats
    for st in stats.values():
        assert np.isfinite(st["mean"]) and np.isfinite(st["abs_max"])


def test_trainer_param_stats_include_grads(monkeypatch, capsys):
    """show_param_stats_period prints grad stats (grads are fetched from

    the step, since grad vars are jit temporaries)."""
    monkeypatch.setattr(FLAGS, "show_param_stats_period", 1)
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    trainer = pt.Trainer(cost=loss)

    def reader():
        yield {"x": np.ones((4, 4), np.float32), "y": np.ones((4, 1), np.float32)}

    trainer.train(reader, num_passes=1)
    out = capsys.readouterr().out
    assert "grad_abs_max" in out and "mean" in out


def test_trainer_param_stats_with_frozen_param(monkeypatch, capsys):
    """A parameter outside minimize()'s slice has no grad var; stats steps

    must not try to fetch one."""
    monkeypatch.setattr(FLAGS, "show_param_stats_period", 1)
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1)
    frozen = pt.layers.fc(x, size=1)  # built but not part of the loss
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    trainer = pt.Trainer(cost=loss)

    def reader():
        yield {"x": np.ones((4, 4), np.float32), "y": np.ones((4, 1), np.float32)}

    trainer.train(reader, num_passes=1)  # must not raise
    assert "grad_abs_max" in capsys.readouterr().out


def test_profiler_exception_passthrough():
    """An exception inside profiler() propagates unchanged."""
    with pytest.raises(RuntimeError, match="boom"):
        with profiler.profiler("/tmp/pt_prof_test"):
            raise RuntimeError("boom")


def test_check_nan_inf_catches(monkeypatch):
    x = pt.layers.data("x", shape=[2])
    y = pt.layers.scale(x, scale=1.0)
    exe = pt.Executor()
    monkeypatch.setattr(FLAGS, "check_nan_inf", True)
    # finite feed passes
    exe.run(feed={"x": np.ones((1, 2), np.float32)}, fetch_list=[y])
    with pytest.raises(FloatingPointError, match="non-finite"):
        exe.run(
            feed={"x": np.array([[np.nan, 1.0]], np.float32)}, fetch_list=[y]
        )


def test_op_path_in_trace_errors():
    """A kernel failure names the op and its outputs (CustomStackTrace)."""
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.fc(x, size=2)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    with pytest.raises(Exception, match="while executing op #.*mul"):
        # feed with the wrong inner dim: the mul kernel raises at trace time
        exe.run(feed={"x": np.ones((2, 5), np.float32)}, fetch_list=[y])


def test_profiler_context_smoke(tmp_path):
    with profiler.profiler(str(tmp_path)):
        import jax.numpy as jnp

        (jnp.ones((4,)) * 2).block_until_ready()


def test_init_api_and_ploter(tmp_path, monkeypatch):
    """v2 paddle.init parity + plot.Ploter parity."""
    monkeypatch.setattr(FLAGS, "log_period", FLAGS.log_period)  # restore after
    monkeypatch.setattr(FLAGS, "seed", FLAGS.seed)
    pt.init(seed=42, log_period=7)
    assert FLAGS.log_period == 7 and FLAGS.seed == 42
    assert pt.default_main_program().random_seed == 42
    # atomic: an unknown flag applies nothing
    with pytest.raises(AttributeError):
        pt.init(enable_timers=True, not_a_flag=1)
    assert FLAGS.enable_timers is False

    from paddle_tpu.plot import Ploter

    p = Ploter("train_cost", "test_cost")
    p.append("train_cost", 0, 1.5)
    p.append("train_cost", 1, 1.2)
    p.append("test_cost", 1, 1.3)
    out = p.plot(str(tmp_path / "curve.png"))
    assert out == str(tmp_path / "curve.png")  # path in both branches
    with pytest.raises(KeyError):
        p.append("nope", 0, 0.0)
    p.reset()
    assert not p.data["train_cost"]
