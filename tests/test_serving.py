"""paddle_tpu.serving: shape-bucketed batching inference server.

The contract under test (ISSUE 1 acceptance): ≥100 mixed-shape
requests compile at most len(buckets) XLA programs with ≥90% cache
hits after warmup, and every bucketed response is numerically
identical to the single-request exact-shape path (padding is sliced
away bit-for-bit). Plus the batcher's coalescing / load-shed /
deadline behavior and the HTTP front-end's endpoints.

One numerics note: padding within a request is bit-exact (asserted
with array_equal below), but rows COALESCED from different requests
run at a different total batch than they would alone, and XLA may
re-associate reductions across program shapes — the coalescing tests
therefore pin to float tolerance, not bits (see PERF.md "Serving").
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.serving import (
    BucketPolicy,
    DeadlineError,
    MicroBatcher,
    ModelRegistry,
    ServingEngine,
    ShedError,
    make_server,
)

# ---------------------------------------------------------------- fixtures --


def _train_dense_model(dirname: str) -> None:
    """Tiny 2-layer MLP regressor, saved as an inference model."""
    pt.reset()
    pt.default_startup_program().random_seed = 3
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    h = pt.layers.fc(x, size=8, act="relu")
    pred = pt.layers.fc(h, size=1)
    cost = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.05).minimize(cost)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    for _ in range(10):
        xv = rng.randn(16, 4).astype(np.float32)
        exe.run(feed={"x": xv, "y": xv.sum(1, keepdims=True)},
                fetch_list=[cost])
    pt.io.save_inference_model(dirname, ["x"], [pred])


def _build_seq_model(dirname: str) -> None:
    """Position-wise model over [B, T, 6] (fc applied per position):
    zero-padded sequence positions cannot leak into real positions, the
    serving contract for seq-bucketed models."""
    pt.reset()
    pt.default_startup_program().random_seed = 3
    x = pt.layers.data("x", shape=[8, 6])  # declared T=8; runtime T varies
    h = pt.layers.fc(x, size=5, act="tanh", num_flatten_dims=2)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(dirname, ["x"], [h])


@pytest.fixture(scope="module")
def dense_model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("serve_dense"))
    _train_dense_model(d)
    return d


@pytest.fixture(scope="module")
def seq_model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("serve_seq"))
    _build_seq_model(d)
    return d


# ---------------------------------------------------------------- engine ----


def test_bucketed_bitmatch_and_bounded_compiles(dense_model_dir):
    """The acceptance criterion: 100 mixed-batch requests → at most
    len(batch_buckets) programs, ≥90% hit rate, every response
    bit-identical to the exact-shape single-request path."""
    eng = ServingEngine(dense_model_dir,
                        policy=BucketPolicy(max_batch_size=16),
                        model_name="acc")
    oracle = ServingEngine(dense_model_dir, model_name="acc_oracle")
    assert eng.policy.batch_buckets == (1, 2, 4, 8, 16)
    rng = np.random.RandomState(1)
    for n in rng.randint(1, 17, size=100):
        xv = rng.randn(n, 4).astype(np.float32)
        got = eng.predict({"x": xv})[0]
        want = oracle.predict({"x": xv}, bucketed=False)[0]
        assert got.shape == (n, 1)
        np.testing.assert_array_equal(got, want)
    s = eng.stats()
    assert s["compiled_programs"] <= len(eng.policy.batch_buckets), s
    assert s["hit_rate"] >= 0.9, s
    assert s["cache_hits"] + s["cache_misses"] == 100
    # engine bucket accounting and executor jit accounting must agree
    assert s["executor_cache"]["misses"] == s["compiled_programs"]


def test_warmup_precompiles_every_bucket(dense_model_dir):
    eng = ServingEngine(dense_model_dir,
                        policy=BucketPolicy(max_batch_size=8),
                        model_name="warm")
    n = eng.warmup()
    assert n == len(eng.policy.batch_buckets) == eng.compiled_programs()
    before = eng.exe.cache_stats["misses"]
    rng = np.random.RandomState(2)
    for k in rng.randint(1, 9, size=20):
        eng.predict({"x": rng.randn(k, 4).astype(np.float32)})
    # zero compiles after warmup: traffic is 100% cache hits
    assert eng.exe.cache_stats["misses"] == before


def test_uniform_dispatch_sync_counters(dense_model_dir):
    """The engine exposes Trainer-parity dispatch/sync counters
    (dispatches_total / syncs_total, ISSUE 6): warmup's pre-compiles
    count as dispatches, every predict is one dispatch + one d2h fence,
    and /stats and the Prometheus render carry the same numbers the
    trainer A/B tests assert on."""
    eng = ServingEngine(dense_model_dir,
                        policy=BucketPolicy(max_batch_size=8),
                        model_name="counters")
    warm = eng.warmup()
    assert eng.dispatches_total == eng.syncs_total == warm
    rng = np.random.RandomState(3)
    for k in (1, 3, 8):
        eng.predict({"x": rng.randn(k, 4).astype(np.float32)})
    s = eng.stats()
    assert s["dispatches_total"] == warm + 3
    assert s["syncs_total"] == warm + 3
    rendered = eng.metrics.render()
    assert "dispatches_total" in rendered and "syncs_total" in rendered


def test_seq_len_buckets(seq_model_dir):
    """Varying [B, T] traffic lands on the (batch × seq) bucket grid;
    padded positions are sliced away and real positions bit-match the
    exact-shape path."""
    pol = BucketPolicy(max_batch_size=4, seq_len_buckets=(4, 8))
    eng = ServingEngine(seq_model_dir, policy=pol, model_name="seq")
    oracle = ServingEngine(seq_model_dir, model_name="seq_oracle")
    rng = np.random.RandomState(3)
    for _ in range(40):
        n = int(rng.randint(1, 5))
        t = int(rng.randint(2, 9))
        xv = rng.randn(n, t, 6).astype(np.float32)
        got = eng.predict({"x": xv})[0]
        want = oracle.predict({"x": xv}, bucketed=False)[0]
        assert got.shape == (n, t, 5)
        np.testing.assert_array_equal(got, want)
    assert eng.compiled_programs() <= pol.max_programs(), eng.stats()


def test_oversized_batch_rejected(dense_model_dir):
    eng = ServingEngine(dense_model_dir,
                        policy=BucketPolicy(max_batch_size=4),
                        model_name="cap")
    with pytest.raises(ValueError, match="exceeds the largest"):
        eng.predict({"x": np.zeros((5, 4), np.float32)})


def test_predictor_delegates_to_engine(dense_model_dir):
    """capi Predictor rides the same bucketed cache: sweeping batch
    sizes compiles per-bucket, not per-size, and raw-buffer IO
    round-trips."""
    from paddle_tpu.capi_support import Predictor

    p = Predictor(dense_model_dir)
    oracle = ServingEngine(dense_model_dir, model_name="pred_oracle")
    rng = np.random.RandomState(4)
    for n in (1, 2, 3, 5, 7, 8):
        xv = rng.randn(n, 4).astype(np.float32)
        blob, shape, dt = p.run_raw(
            ["x"], [xv.tobytes()], [list(xv.shape)], ["float32"], 0)
        got = np.frombuffer(blob, np.dtype(dt)).reshape(shape)
        want = oracle.predict({"x": xv}, bucketed=False)[0]
        np.testing.assert_array_equal(got, want)
    # 6 batch sizes -> buckets {1, 2, 4, 8}
    assert p.engine.compiled_programs() <= 4


# --------------------------------------------------------------- batcher ----


def test_batcher_coalesces_queued_requests(dense_model_dir):
    """Requests queued before the worker starts coalesce into ONE
    engine call (deterministic coalescing — no timing races)."""
    eng = ServingEngine(dense_model_dir,
                        policy=BucketPolicy(max_batch_size=16),
                        model_name="coal")
    oracle = ServingEngine(dense_model_dir, model_name="coal_oracle")
    b = MicroBatcher(eng, max_wait_ms=10, max_queue=16)
    rng = np.random.RandomState(5)
    reqs = [rng.randn(1, 4).astype(np.float32) for _ in range(6)]
    futs = [b.submit({"x": r}) for r in reqs]
    b.start()
    results = [f.result(timeout=30) for f in futs]
    b.stop()
    assert eng.cache_hits + eng.cache_misses == 1  # one coalesced call
    assert b._batch_hist.count == 1 and b._batch_hist.sum == 6
    for r, xv in zip(results, reqs):
        want = oracle.predict({"x": xv}, bucketed=False)[0]
        assert r[0].shape == want.shape
        # coalesced rows run at a different batch size than they would
        # alone; XLA may re-associate reductions across program shapes
        np.testing.assert_allclose(r[0], want, rtol=1e-5, atol=1e-6)


def test_batcher_concurrent_clients(dense_model_dir):
    """8 threads × 3 requests each against a running batcher: all
    correct, and coalescing did happen (fewer engine calls than
    requests)."""
    eng = ServingEngine(dense_model_dir,
                        policy=BucketPolicy(max_batch_size=32),
                        model_name="conc")
    eng.warmup()
    calls0 = eng.cache_hits + eng.cache_misses
    oracle = ServingEngine(dense_model_dir, model_name="conc_oracle")
    b = MicroBatcher(eng, max_wait_ms=30, max_queue=64).start()
    rng = np.random.RandomState(6)
    inputs = [rng.randn(2, 4).astype(np.float32) for _ in range(24)]
    outs: dict = {}
    errs = []

    def client(i):
        try:
            for j in range(3):
                k = i * 3 + j
                outs[k] = b.predict({"x": inputs[k]}, timeout_ms=20000)
        except Exception as e:  # pragma: no cover - diagnostic
            errs.append(e)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    b.stop()
    assert not errs, errs
    assert len(outs) == 24
    for k, res in outs.items():
        want = oracle.predict({"x": inputs[k]}, bucketed=False)[0]
        np.testing.assert_allclose(res[0], want, rtol=1e-5, atol=1e-6)
    engine_calls = eng.cache_hits + eng.cache_misses - calls0
    assert engine_calls < 24, f"no coalescing: {engine_calls} calls"


def test_queue_full_sheds_instead_of_hanging(dense_model_dir):
    eng = ServingEngine(dense_model_dir, model_name="shed")
    b = MicroBatcher(eng, max_queue=2)  # worker NOT started
    b.submit({"x": np.zeros((1, 4), np.float32)})
    b.submit({"x": np.zeros((1, 4), np.float32)})
    t0 = time.monotonic()
    with pytest.raises(ShedError, match="queue full"):
        b.submit({"x": np.zeros((1, 4), np.float32)})
    assert time.monotonic() - t0 < 1.0  # rejected immediately, no wait
    assert b.metrics.counter_value("shed_total") >= 1
    b.stop()  # queued requests fail with ShedError on shutdown


def test_deadline_exceeded_while_queued(dense_model_dir):
    eng = ServingEngine(dense_model_dir, model_name="dl")
    b = MicroBatcher(eng, max_queue=8)  # worker not started yet
    fut = b.submit({"x": np.zeros((1, 4), np.float32)}, timeout_ms=10)
    time.sleep(0.05)  # let the deadline lapse, then start the worker
    b.start()
    with pytest.raises(DeadlineError):
        fut.result(timeout=30)
    b.stop()


# ----------------------------------------------------------------- server ---


@pytest.fixture()
def http_stack(dense_model_dir):
    reg = ModelRegistry()
    eng, _ = reg.add("default", model_dir=dense_model_dir,
                     policy=BucketPolicy(max_batch_size=16),
                     max_wait_ms=5.0, timeout_ms=20000.0)
    eng.warmup()
    srv = make_server(reg)
    srv.serve_background()
    yield reg, srv, f"http://127.0.0.1:{srv.port}"
    srv.shutdown()
    reg.stop()
    srv.server_close()


def _post(url, payload):
    body = json.dumps(payload).encode()
    req = urllib.request.Request(
        url, data=body, headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        return json.load(r)


def test_http_predict_healthz_metrics(http_stack, dense_model_dir):
    reg, srv, url = http_stack
    with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
        h = json.load(r)
    assert h["status"] == "ok" and h["models"] == ["default"]

    oracle = ServingEngine(dense_model_dir, model_name="http_oracle")
    rng = np.random.RandomState(7)
    for n in (1, 3, 8):
        xv = rng.randn(n, 4).astype(np.float32)
        out = _post(url + "/predict", {"inputs": {"x": xv.tolist()}})
        (vals,) = out["outputs"].values()
        want = oracle.predict({"x": xv}, bucketed=False)[0]
        np.testing.assert_allclose(
            np.asarray(vals, np.float32), want, rtol=1e-5, atol=1e-6)

    with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
        metrics = r.read().decode()
    # the ISSUE-named surface: cache hit accounting + latency stats
    assert "ptserving_compile_cache_hits_total" in metrics
    assert "ptserving_engine_run_seconds_bucket" in metrics
    assert "ptserving_engine_run_seconds_p99" in metrics
    assert "ptserving_batch_rows" in metrics
    assert "ptserving_queue_depth" in metrics

    with urllib.request.urlopen(url + "/stats", timeout=30) as r:
        stats = json.load(r)
    assert stats["default"]["compiled_programs"] <= 5


def test_http_errors(http_stack):
    reg, srv, url = http_stack
    # unknown model → 404
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url + "/predict/nope", {"inputs": {"x": [[0, 0, 0, 0]]}})
    assert ei.value.code == 404
    # malformed body → 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url + "/predict", {"not_inputs": 1})
    assert ei.value.code == 400
    # missing feed → 400
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url + "/predict", {"inputs": {"bogus": [1.0]}})
    assert ei.value.code == 400


def test_http_shed_and_deadline(dense_model_dir):
    """A stuck model (worker never started, queue of 1): the first
    request times out with 504, an overflowing one sheds with 503."""
    reg = ModelRegistry()
    eng = ServingEngine(dense_model_dir, model_name="stuck",
                        metrics=reg.metrics)
    stuck = MicroBatcher(eng, max_queue=1, metrics=reg.metrics)
    reg.add("stuck", engine=eng, batcher=stuck)
    srv = make_server(reg)
    # serve WITHOUT starting batchers (srv thread only)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    url = f"http://127.0.0.1:{srv.port}/predict/stuck"
    codes = {}

    def slow():
        try:
            _post(url, {"inputs": {"x": [[0, 0, 0, 0]]},
                        "timeout_ms": 300})
            codes["a"] = 200
        except urllib.error.HTTPError as e:
            codes["a"] = e.code

    ta = threading.Thread(target=slow)
    ta.start()
    time.sleep(0.1)  # first request now occupies the only queue slot
    try:
        _post(url, {"inputs": {"x": [[0, 0, 0, 0]]}, "timeout_ms": 300})
        codes["b"] = 200
    except urllib.error.HTTPError as e:
        codes["b"] = e.code
    ta.join(timeout=30)
    srv.shutdown()
    srv.server_close()
    assert codes["b"] == 503, codes
    assert codes["a"] == 504, codes


# -------------------------------------------- ISSUE 9: fleet plumbing -------


def test_healthz_reports_load_block(http_stack):
    """/healthz carries the load block a join-shortest-queue router
    scores replicas by: queue depth, slot occupancy, and the uniform
    dispatch/sync counters — no /metrics scrape needed."""
    reg, srv, url = http_stack
    with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
        payload = json.load(r)
    before = payload["load"]
    for k in ("queue_depth", "queue_age_ms", "active_slots", "max_slots",
              "slot_occupancy", "first_token_p99_ms", "dispatches_total",
              "syncs_total", "classes", "models"):
        assert k in before, before
    # per-model breakdown (ISSUE 16 satellite): each served model gets
    # its own queue_depth/age + SLO-class split, and /healthz carries
    # the artifact fingerprint the rollout verify gate checks
    assert set(before["models"]) == {"default"}
    m = before["models"]["default"]
    for k in ("queue_depth", "queue_age_ms", "classes", "slo_class"):
        assert k in m, m
    assert set(before["classes"]) == {"interactive", "batch"}
    assert payload["versions"]["default"]
    _post(url + "/predict", {"inputs": {"x": [[0.1, 0.2, 0.3, 0.4]]}})
    with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
        after = json.load(r)["load"]
    assert after["dispatches_total"] > before["dispatches_total"]
    assert after["syncs_total"] > before["syncs_total"]
    assert after["queue_depth"] == 0  # nothing waiting at rest
    assert after["queue_age_ms"] == 0.0  # empty queue has no age


def test_predict_adopts_request_id_header(http_stack):
    """The router-hop correlation satellite: a forwarded
    X-PT-Request-Id is adopted for the /predict MicroBatcher path and
    echoed on the response; absent the header, the replica mints one."""
    from paddle_tpu.serving import REQUEST_ID_HEADER

    reg, srv, url = http_stack
    body = json.dumps(
        {"inputs": {"x": [[0.1, 0.2, 0.3, 0.4]]}}).encode()
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json",
                 REQUEST_ID_HEADER: "rt-777"})
    with urllib.request.urlopen(req, timeout=60) as r:
        assert r.headers.get(REQUEST_ID_HEADER) == "rt-777"
        json.load(r)
    req = urllib.request.Request(
        url + "/predict", data=body,
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as r:
        minted = r.headers.get(REQUEST_ID_HEADER)
        json.load(r)
    assert minted  # replica minted its own


def test_batcher_submit_adopts_request_id(dense_model_dir):
    """Unit-level: MicroBatcher.submit(request_id=...) threads the id
    into its _Request (the /predict path's correlation key; before
    ISSUE 9 only the generation path carried caller-provided ids)."""
    from paddle_tpu.serving.batcher import _Request

    r = _Request({"x": np.zeros((1, 4), np.float32)}, deadline=1.0,
                 request_id="rt-42")
    assert r.request_id == "rt-42"
    r2 = _Request({"x": np.zeros((1, 4), np.float32)}, deadline=1.0)
    assert r2.request_id and r2.request_id != "rt-42"


# -------------------------------------- ISSUE 9: mesh-sharded inference -----


def _build_sharded_model(dirname: str) -> None:
    """Vocab-sharded embedding (rows striped over `mp`) + fc head:
    the partition spec must survive save→load via the meta.json
    sharding sidecar."""
    from paddle_tpu.parallel import sharded_embedding

    pt.reset()
    pt.default_startup_program().random_seed = 3
    ids = pt.layers.data("ids", shape=[6], dtype="int64")
    emb = sharded_embedding(ids, size=[32, 16])
    h = pt.layers.fc(emb, size=8, act="tanh", num_flatten_dims=2)
    out = pt.layers.fc(h, size=4, num_flatten_dims=2)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(dirname, ["ids"], [out])


def test_sharding_sidecar_roundtrip(tmp_path):
    """save_inference_model records partition specs in meta.json;
    load_inference_model re-attaches them to the restored vars."""
    d = str(tmp_path / "sharded")
    _build_sharded_model(d)
    with open(d + "/meta.json") as f:
        meta = json.load(f)
    assert meta["sharding"]["mesh_axes"] == ["mp"]
    (name, spec), = meta["sharding"]["specs"].items()
    assert spec == ["mp", None]
    prog, feeds, fetches = pt.io.load_inference_model(d, scope=pt.Scope())
    from jax.sharding import PartitionSpec

    v = prog.global_block().var(name)
    assert v.sharding == PartitionSpec("mp", None)


def test_mesh_replica_bit_identical_to_single_device(tmp_path):
    """THE ISSUE 9 sharded-inference acceptance: the same artifact
    served by a mesh replica (dp1,mp2 — embedding table striped over
    2 devices) returns outputs BIT-identical to the single-device
    engine, across batch buckets, including warmup."""
    from paddle_tpu.parallel import mesh_from_spec

    d = str(tmp_path / "sharded")
    _build_sharded_model(d)
    single = ServingEngine(d, policy=BucketPolicy(max_batch_size=4),
                           model_name="one_chip")
    mesh = mesh_from_spec("dp1,mp2")
    meshed = ServingEngine(d, policy=BucketPolicy(max_batch_size=4),
                           model_name="mesh", mesh=mesh)
    assert meshed.warmup() == len(meshed.policy.batch_buckets)
    rng = np.random.RandomState(5)
    for n in (1, 2, 3, 4):
        iv = rng.randint(0, 32, size=(n, 6)).astype(np.int64)
        a = single.predict({"ids": iv})[0]
        b = meshed.predict({"ids": iv})[0]
        assert b.shape == (n, 6, 4)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    s = meshed.stats()
    assert s["mesh"]["axes"] == {"dp": 1, "mp": 2}
    assert s["mesh"]["sharded_params"]


def test_mesh_missing_axis_rejected(tmp_path):
    """A serving mesh without the axes the artifact shards over must
    fail loudly at load, not silently serve unsharded."""
    from paddle_tpu.parallel import mesh_from_spec

    d = str(tmp_path / "sharded")
    _build_sharded_model(d)
    with pytest.raises(ValueError, match="mp"):
        ServingEngine(d, model_name="bad",
                      mesh=mesh_from_spec("dp2"))
