"""Chaos harness: crash/corrupt/resume end-to-end (ISSUE 4 acceptance).

The headline case SIGKILLs a real training subprocess mid-pass (via the
deterministic `executor.step` kill fault — the process dies with the
SIGKILL status 137 and zero chance to clean up), corrupts the newest
checkpoint it left behind, resumes, and asserts the run completes with
parameters BIT-IDENTICAL to an uninterrupted run: the recovery path is
correct, not approximately correct.

Subprocess cases cost a few seconds of jax import each; the SIGTERM
preemption e2e is additionally marked `slow` (tier-1 covers the same
machinery in-process, test_resilience.py). The sharded chaos case runs
in-process on the 8-device CPU mesh.
"""

import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import io as pio


TRAIN_SCRIPT = """
import sys
import time
import numpy as np
import paddle_tpu as pt

ckpt_dir, num_passes, out = sys.argv[1], int(sys.argv[2]), sys.argv[3]
# optional per-batch stall so a test can land a signal mid-training
sleep_s = float(sys.argv[4]) if len(sys.argv) > 4 else 0.0

x = pt.layers.data("x", shape=[4])
y = pt.layers.data("y", shape=[1])
pred = pt.layers.fc(x, size=1, param_attr=pt.ParamAttr(name="w"),
                    bias_attr=False)
loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
pt.init(seed=7)

def reader():
    for i in range(8):
        if sleep_s:
            time.sleep(sleep_s)
        rng = np.random.RandomState(100 + i)
        xs = rng.randn(8, 4).astype(np.float32)
        yield {"x": xs, "y": xs.sum(1, keepdims=True).astype(np.float32)}

cc = pt.CheckpointConfig(ckpt_dir, epoch_interval=0, step_interval=2,
                         max_num_checkpoints=100)
t = pt.Trainer(loss, checkpoint_config=cc)
try:
    t.train(reader, num_passes=num_passes)
except pt.resilience.PreemptedError as e:
    # what the CLI train command does: EX_TEMPFAIL for the scheduler
    print("PREEMPTED:", e, flush=True)
    sys.exit(pt.resilience.PREEMPT_EXIT_CODE)
np.savez(out, w=np.asarray(pt.global_scope().get("w")),
         step=np.int64(t.step))
print("DONE step", t.step, flush=True)
"""


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _chaos_env(fault_spec=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # `python script.py` puts the SCRIPT's dir on sys.path, not our cwd
    env["PYTHONPATH"] = REPO_ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.pop("PT_FLAGS_FAULT_SPEC", None)
    if fault_spec:
        env["PT_FLAGS_FAULT_SPEC"] = fault_spec
    return env


def _run_script(script_path, args, fault_spec=None, timeout=180):
    return subprocess.run(
        [sys.executable, script_path, *map(str, args)],
        env=_chaos_env(fault_spec), capture_output=True, text=True,
        timeout=timeout)


@pytest.fixture
def train_script(tmp_path):
    p = tmp_path / "train_job.py"
    p.write_text(TRAIN_SCRIPT)
    return str(p)


@pytest.mark.chaos
def test_sigkill_midpass_corrupt_newest_resume_bitexact(
        train_script, tmp_path):
    """The acceptance e2e: kill -9 mid-pass, rot the newest checkpoint,
    resume → final params identical to a never-interrupted run."""
    # 1) uninterrupted reference run (3 passes × 8 batches = 24 steps)
    ref_out = str(tmp_path / "ref.npz")
    r = _run_script(train_script, [str(tmp_path / "ck_ref"), 3, ref_out])
    assert r.returncode == 0, r.stderr

    # 2) the victim: an uncatchable kill at the 11th step (mid-pass 1)
    d = str(tmp_path / "ck")
    r = _run_script(train_script, [d, 3, str(tmp_path / "never.npz")],
                    fault_spec="executor.step:hit=11:action=kill")
    assert r.returncode == 137, (r.returncode, r.stderr)  # SIGKILL status
    assert not os.path.exists(str(tmp_path / "never.npz"))
    newest = pio.get_latest_checkpoint_serial(d)
    assert newest >= 1, "the victim checkpointed before dying"

    # 3) bit-rot the newest checkpoint (meta marker stays present)
    p = os.path.join(d, f"checkpoint_{newest}", pio.PARAMS_FILE)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)

    # 4) resume: must quarantine the rotten serial, restore the previous
    # one, and train to completion
    res_out = str(tmp_path / "res.npz")
    r = _run_script(train_script, [d, 3, res_out])
    assert r.returncode == 0, r.stderr
    assert os.path.isdir(os.path.join(d, f"checkpoint_{newest}.corrupt"))

    ref, res = np.load(ref_out), np.load(res_out)
    assert int(ref["step"]) == int(res["step"]) == 24
    np.testing.assert_array_equal(ref["w"], res["w"])


@pytest.mark.chaos
@pytest.mark.slow
def test_sigterm_preemption_resume_e2e(train_script, tmp_path):
    """Graceful preemption: SIGTERM → finish batch → emergency
    checkpoint → exit 75 (EX_TEMPFAIL); a rerun resumes and finishes
    with params identical to an uninterrupted run."""
    ref_out = str(tmp_path / "ref.npz")
    r = _run_script(train_script, [str(tmp_path / "ck_ref"), 3, ref_out])
    assert r.returncode == 0, r.stderr

    d = str(tmp_path / "ck")
    # 0.2s per batch keeps the victim inside train() long enough for
    # the signal to land mid-pass deterministically
    proc = subprocess.Popen(
        [sys.executable, train_script, d, "30",
         str(tmp_path / "never.npz"), "0.2"],
        env=_chaos_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    # preempt once training has demonstrably started (first cadence save)
    deadline = time.monotonic() + 120
    while (pio.get_latest_checkpoint_serial(d) < 0
           and time.monotonic() < deadline):
        time.sleep(0.1)
        if proc.poll() is not None:
            break
    assert pio.get_latest_checkpoint_serial(d) >= 0, proc.communicate()[1]
    proc.send_signal(signal.SIGTERM)
    out, err = proc.communicate(timeout=120)
    from paddle_tpu.resilience import PREEMPT_EXIT_CODE

    assert proc.returncode == PREEMPT_EXIT_CODE, (proc.returncode, err)
    assert "PREEMPTED" in out
    # the emergency checkpoint carries the exact mid-pass position
    args = json.load(open(os.path.join(
        d, f"checkpoint_{pio.get_latest_checkpoint_serial(d)}",
        pio.META_FILE)))["trainer_args"]
    assert args["step"] >= 1 and args.get("mid_pass")

    res_out = str(tmp_path / "res.npz")
    r = _run_script(train_script, [d, 3, res_out])
    assert r.returncode == 0, r.stderr
    ref, res = np.load(ref_out), np.load(res_out)
    assert int(res["step"]) == 24
    np.testing.assert_array_equal(ref["w"], res["w"])


# ------------------------------------- background checkpointing (in-proc)


@pytest.mark.chaos
def test_background_checkpoint_sigterm_drains_cleanly(tmp_path):
    """ISSUE 5: checkpoints commit on a background writer thread; a
    SIGTERM preemption must drain it — the emergency checkpoint (and
    every cadence one before it) is fully committed, hash-verified, with
    no torn tmp files, BEFORE PreemptedError reaches the caller."""
    import threading

    d = str(tmp_path / "ck")
    pt.reset()
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1, param_attr=pt.ParamAttr(name="w"),
                        bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)

    def reader():
        for i in range(10):
            rng = np.random.RandomState(i)
            xs = rng.randn(8, 4).astype(np.float32)
            yield {"x": xs, "y": xs.sum(1, keepdims=True)}

    cc = pt.CheckpointConfig(d, epoch_interval=0, step_interval=1,
                             max_num_checkpoints=100)
    assert cc.background  # the async commit path is the default
    t = pt.Trainer(loss, checkpoint_config=cc)

    def preempt_at_4(e):
        if isinstance(e, pt.EndIteration) and e.step == 4:
            os.kill(os.getpid(), signal.SIGTERM)

    with pytest.raises(pt.resilience.PreemptedError, match="SIGTERM"):
        # coarse sync cadence: checkpoints + preemption must not depend
        # on the per-step fences of the legacy loop
        t.train(reader, num_passes=3, event_handler=preempt_at_4,
                log_interval=8)
    # writer idle and its thread quiesced — nothing is still writing
    assert t._ckpt_writer._idle.is_set()
    # every serial is complete and hash-valid, incl. the emergency one
    latest = pio.get_latest_checkpoint_serial(d)
    assert latest >= 1
    for name in os.listdir(d):
        sd = os.path.join(d, name)
        if os.path.isdir(sd):
            pio.verify_checkpoint(sd)
        assert not name.endswith(".tmp"), "torn background write left over"
    for name in os.listdir(os.path.join(d, f"checkpoint_{latest}")):
        assert not name.endswith(".tmp")
    # the emergency checkpoint carries the mid-pass resume position
    args = json.load(open(os.path.join(
        d, f"checkpoint_{latest}", pio.META_FILE)))["trainer_args"]
    assert args["step"] == 4 and args.get("mid_pass")
    # and a resume picks it up exactly (no threads from the dead run)
    assert threading.active_count() < 20
    pt.reset_global_scope()
    t2 = pt.Trainer(loss, checkpoint_config=cc)
    t2.init()
    assert t2.step == 4 and t2._resume_batch == 4


@pytest.mark.chaos
def test_background_checkpoint_write_failure_surfaces(tmp_path):
    """An injected ckpt.write failure on the writer thread must fail the
    training run (at the next submit/drain), not vanish into a daemon."""
    from paddle_tpu.resilience import faults

    d = str(tmp_path / "ck")
    pt.reset()
    faults.arm("ckpt.write", hit=1, action="raise")
    try:
        x = pt.layers.data("x", shape=[4])
        pred = pt.layers.fc(x, size=1)
        loss = pt.layers.mean(pred)
        pt.optimizer.SGD(learning_rate=0.1).minimize(loss)

        def reader():
            for i in range(6):
                yield {"x": np.ones((4, 4), np.float32)}

        cc = pt.CheckpointConfig(d, epoch_interval=0, step_interval=2)
        t = pt.Trainer(loss, checkpoint_config=cc)
        with pytest.raises(RuntimeError, match="background checkpoint"):
            t.train(reader, num_passes=1, log_interval=8)
    finally:
        faults.disarm()


# ------------------------------------------------- sharded chaos (in-proc)


@pytest.mark.chaos
def test_sharded_corrupt_shard_falls_back_and_quarantines(tmp_path):
    """Satellite: corrupt one shards_p*.npz of the newest sharded
    serial — load must fall back to the previous serial and quarantine
    the bad one."""
    import jax
    from jax.sharding import PartitionSpec

    from paddle_tpu import parallel as pp

    assert len(jax.devices()) == 8
    mesh = pp.make_mesh((4, 2), ("dp", "mp"))
    pt.reset()
    x = pt.layers.data("x", shape=[16])
    y = pt.layers.data("y", shape=[1])
    h = pt.layers.fc(x, size=64, act="relu",
                     param_attr=pt.ParamAttr(name="w1"), bias_attr=False)
    pred = pt.layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w2"),
                        bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
    prog = pt.default_main_program()
    prog.global_block().var("w1").sharding = PartitionSpec(None, "mp")
    prog.random_seed = 3
    pt.default_startup_program().random_seed = 3
    exe = pp.ParallelExecutor(mesh, shard_optimizer_state=True)
    pt.Executor().run(pt.default_startup_program())

    def feed(step):
        rng = np.random.RandomState(step)
        return {"x": rng.randn(16, 16).astype(np.float32),
                "y": rng.randn(16, 1).astype(np.float32)}

    d = str(tmp_path / "ck")
    exe.run(prog, feed=feed(0), fetch_list=[loss])
    pio.save_checkpoint(d, {"step": 1}, prog, sharded=True)
    w1_at_1 = np.asarray(pt.global_scope().get("w1")).copy()
    exe.run(prog, feed=feed(1), fetch_list=[loss])
    pio.save_checkpoint(d, {"step": 2}, prog, sharded=True)

    shard = os.path.join(d, "checkpoint_1", "shards_p0.npz")
    assert os.path.exists(shard)
    with open(shard, "r+b") as f:
        f.truncate(os.path.getsize(shard) // 2)

    pt.reset_global_scope()
    with pytest.warns(UserWarning, match="quarantined"):
        args = pio.load_checkpoint(d, prog)
    assert args["step"] == 1
    assert os.path.isdir(os.path.join(d, "checkpoint_1.corrupt"))
    np.testing.assert_array_equal(
        np.asarray(pt.global_scope().get("w1")), w1_at_1)


@pytest.mark.chaos
def test_sharded_injected_shard_corruption(tmp_path):
    """ckpt.write corrupt fires on the SHARD write path too."""
    import jax

    from paddle_tpu import parallel as pp
    from paddle_tpu.resilience import faults

    assert len(jax.devices()) == 8
    pp.make_mesh((4, 2), ("dp", "mp"))
    pt.reset()
    x = pt.layers.data("x", shape=[4])
    pred = pt.layers.fc(x, size=1, param_attr=pt.ParamAttr(name="w"),
                        bias_attr=False)
    loss = pt.layers.mean(pred)
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    prog = pt.default_main_program()
    pt.Executor().run(pt.default_startup_program())

    d = str(tmp_path / "ck")
    pio.save_checkpoint(d, {"step": 1}, prog, sharded=True)
    faults.arm("ckpt.write", hit=1, action="corrupt")
    pio.save_checkpoint(d, {"step": 2}, prog, sharded=True)
    faults.disarm()
    assert faults.stats()["ckpt.write"]["fired"] == 1
    with pytest.warns(UserWarning, match="quarantined"):
        assert pio.load_checkpoint(d, prog)["step"] == 1


# ------------------------------------ elastic restart on a new mesh shape


@pytest.mark.chaos
def test_sigterm_sharded_restart_on_different_mesh_bitwise(tmp_path):
    """ISSUE 14 acceptance: SIGTERM lands mid-pass in a dp-sharded
    (ZeRO optimizer state) run whose checkpoints commit sharded on the
    background writer; the restart happens on a DIFFERENT mesh shape
    (dp8 -> dp4x2) and must end with parameters BIT-IDENTICAL to an
    uninterrupted reference that checkpoints and switches mesh at the
    same step — the emergency path and the elastic reshard are both
    exact, not approximately correct."""
    import jax

    from paddle_tpu import parallel as pp

    assert len(jax.devices()) == 8

    def build():
        pt.reset()
        pt.default_main_program().random_seed = 13
        pt.default_startup_program().random_seed = 13
        x = pt.layers.data("x", shape=[8])
        y = pt.layers.data("y", shape=[1])
        h = pt.layers.fc(x, size=16, act="relu")
        pred = pt.layers.fc(h, size=1)
        loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
        return loss

    def batches(lo, hi):
        def reader():
            for i in range(lo, hi):
                rng = np.random.RandomState(100 + i)
                xs = rng.randn(8, 8).astype(np.float32)
                yield {"x": xs, "y": xs.sum(1, keepdims=True)}
        return reader

    def exe_on(spec):
        return pp.ParallelExecutor(pp.mesh_from_spec(spec),
                                   shard_optimizer_state=True)

    def host_params():
        return {n: np.asarray(pt.global_scope().get(n))
                for n in sorted(pt.global_scope().keys())
                if not n.startswith("@")}

    # --- interrupted arm: dp8, SIGTERM after batch 2, emergency
    # sharded checkpoint on the background writer ----------------------
    d = str(tmp_path / "ck")
    loss = build()
    cc = pt.CheckpointConfig(d, epoch_interval=0, sharded=True)
    assert cc.background
    t = pt.Trainer(loss, checkpoint_config=cc, executor=exe_on("dp8"))

    def preempt_at_3(e):
        if isinstance(e, pt.EndIteration) and e.step == 3:
            os.kill(os.getpid(), signal.SIGTERM)

    with pytest.raises(pt.resilience.PreemptedError, match="SIGTERM"):
        t.train(batches(0, 6), num_passes=1, event_handler=preempt_at_3,
                log_interval=1)
    assert t._ckpt_writer._idle.is_set()  # emergency commit fully drained
    args = json.load(open(os.path.join(
        d, f"checkpoint_{pio.get_latest_checkpoint_serial(d)}",
        pio.META_FILE)))["trainer_args"]
    assert args["step"] == 3 and args["mid_pass"]

    # restart on dp4x2: resumes pass 0 at batch 3, finishes the pass
    loss = build()
    t2 = pt.Trainer(loss, checkpoint_config=pt.CheckpointConfig(
        d, epoch_interval=0, sharded=True), executor=exe_on("dp4,mp2"))
    t2.train(batches(0, 6), num_passes=1, log_interval=1)
    assert t2.step == 6
    interrupted = host_params()

    # --- reference arm: same schedule, no SIGTERM — 3 batches on dp8,
    # checkpoint, then batches 3..5 on dp4x2 ---------------------------
    d_ref = str(tmp_path / "ck_ref")
    loss = build()
    tr1 = pt.Trainer(loss, executor=exe_on("dp8"))
    tr1.train(batches(0, 3), num_passes=1, log_interval=1)
    pio.save_checkpoint(d_ref, {"step": 3}, pt.default_main_program(),
                        sharded=True)
    loss = build()
    tr2 = pt.Trainer(loss, checkpoint_config=pt.CheckpointConfig(
        d_ref, epoch_interval=0, sharded=True), executor=exe_on("dp4,mp2"))
    tr2.train(batches(3, 6), num_passes=1, log_interval=1)
    ref = host_params()

    assert set(interrupted) == set(ref)
    bad = [n for n in ref
           if not np.array_equal(ref[n], interrupted[n])]
    assert not bad, f"elastic restart diverged from reference: {bad[:6]}"
