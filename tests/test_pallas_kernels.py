"""Pallas fused LSTM/GRU kernel tests (interpret mode on CPU).

Reference analogue: gserver/tests/test_LayerGrad.cpp runs each fused CUDA
kernel against the plain implementation — here the pallas kernels must
match the lax.scan formulation in both outputs and gradients (the scan IS
the backward via custom_vjp, so grads must also match finite differences
of the pallas forward).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.lod import LoDArray
from paddle_tpu.flags import FLAGS
from paddle_tpu.ops import pallas_kernels, rnn_ops

B, H, T = 8, 128, 5


def _mask(lengths, T=T, B=B):
    m = np.zeros((T, B), bool)
    for b, L in enumerate(lengths):
        m[:L, b] = True
    return jnp.asarray(m)


def test_lstm_fused_matches_scan():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(T, B, 4 * H).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(H, 4 * H).astype(np.float32) * 0.1)
    mask = _mask([5, 3, 1, 4, 5, 2, 5, 5])
    h_f, (hT_f, cT_f) = pallas_kernels.lstm_fused(x, mask, w)
    h_s, (hT_s, cT_s) = rnn_ops.lstm_scan(x, mask, w, None)
    np.testing.assert_allclose(h_f, h_s, atol=1e-5)
    np.testing.assert_allclose(hT_f, hT_s, atol=1e-5)
    np.testing.assert_allclose(cT_f, cT_s, atol=1e-5)


def test_lstm_fused_grads_match_scan():
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(T, B, 4 * H).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(H, 4 * H).astype(np.float32) * 0.1)
    mask = _mask([5, 2, 4, 5, 3, 5, 1, 5])

    def loss_f(x, w):
        h, (hT, cT) = pallas_kernels.lstm_fused(x, mask, w)
        return jnp.sum(h**2) + jnp.sum(hT * cT)

    def loss_s(x, w):
        h, (hT, cT) = rnn_ops.lstm_scan(x, mask, w, None)
        return jnp.sum(h**2) + jnp.sum(hT * cT)

    gx_f, gw_f = jax.grad(loss_f, argnums=(0, 1))(x, w)
    gx_s, gw_s = jax.grad(loss_s, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(gx_f, gx_s, atol=1e-4)
    np.testing.assert_allclose(gw_f, gw_s, atol=1e-4)


def test_gru_fused_matches_scan():
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(T, B, 3 * H).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(H, 3 * H).astype(np.float32) * 0.1)
    mask = _mask([5, 3, 1, 4, 5, 2, 5, 5])
    h_f, hT_f = pallas_kernels.gru_fused(x, mask, w)
    h_s, hT_s = rnn_ops.gru_scan(x, mask, w, None)
    np.testing.assert_allclose(h_f, h_s, atol=1e-5)
    np.testing.assert_allclose(hT_f, hT_s, atol=1e-5)


def test_dynamic_lstm_layer_uses_fused_and_converges(monkeypatch):
    """End to end through the layer DSL with eligible shapes; flag off

    must give (near-)identical loss. H must sit inside lstm_supported's
    measured perf window (384..640) or the fused branch silently runs the
    scan and the comparison is vacuous — a dispatch spy guards that."""
    HE = 512  # eligible hidden size (module-level H=128 is NOT eligible)
    losses = {}
    fused_calls = []
    orig = pallas_kernels.lstm_fused
    monkeypatch.setattr(
        pallas_kernels, "lstm_fused",
        lambda *a, **k: (fused_calls.append(1), orig(*a, **k))[1],
    )
    monkeypatch.setattr(FLAGS, "fused_rnn_interpret", True)
    for fused in (True, False):
        pt.reset()
        monkeypatch.setattr(FLAGS, "use_fused_rnn", fused)
        x = pt.layers.data("x", shape=[-1, 4 * HE], lod_level=1,
                           append_batch_size=False)
        label = pt.layers.data("label", shape=[-1, 1], dtype=np.int32,
                               append_batch_size=False)
        hidden = pt.layers.dynamic_lstm(x, size=4 * HE, max_len=8)
        last = pt.layers.sequence_last_step(hidden)
        logits = pt.layers.fc(last, size=2)
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
        prog = pt.default_main_program()
        prog.random_seed = 3
        pt.default_startup_program().random_seed = 3
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        rng = np.random.RandomState(4)
        seqs = [rng.randn(rng.randint(2, 7), 4 * HE).astype(np.float32) * 0.1
                for _ in range(B)]
        lab = np.array([[i % 2] for i in range(B)], np.int32)
        lod = LoDArray.from_sequences(seqs, bucket=64, max_seqs=B)
        ls = []
        for _ in range(6):
            (l,) = exe.run(feed={"x": lod, "label": lab}, fetch_list=[loss])
            ls.append(float(l))
        assert ls[-1] < ls[0]
        losses[fused] = ls
        if fused:
            assert fused_calls, "fused path did not dispatch — vacuous test"
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-3)


def test_support_gating(monkeypatch):
    # on CPU the fused path is only eligible with the test override
    assert not pallas_kernels.lstm_supported(8, 512, "sigmoid", "tanh", "tanh", None)
    monkeypatch.setattr(FLAGS, "fused_rnn_interpret", True)
    assert pallas_kernels.lstm_supported(8, 512, "sigmoid", "tanh", "tanh", None)
    assert not pallas_kernels.lstm_supported(7, 512, "sigmoid", "tanh", "tanh", None)
    assert not pallas_kernels.lstm_supported(8, 100, "sigmoid", "tanh", "tanh", None)
    # outside the measured perf window (rnn_kernel_microbench.json: scan
    # wins at H=256); the VMEM model gates by (B, H, dtype): bf16 H=1280
    # fits at B=64 but not B=128 (observed train-graph overflow), and the
    # f32 weight block alone busts the budget at H=1280
    assert not pallas_kernels.lstm_supported(8, 256, "sigmoid", "tanh", "tanh", None)
    assert pallas_kernels.lstm_supported(32, 1280, "sigmoid", "tanh", "tanh", None)
    # B=64 H=1280 bf16 models at 15.9M: observed flipping between
    # compiling and overflowing on different compiles — excluded
    assert not pallas_kernels.lstm_supported(64, 1280, "sigmoid", "tanh", "tanh", None)
    assert not pallas_kernels.lstm_supported(128, 1280, "sigmoid", "tanh", "tanh", None)
    assert pallas_kernels.lstm_supported(128, 1024, "sigmoid", "tanh", "tanh", None)
    assert not pallas_kernels.lstm_supported(
        128, 1024, "sigmoid", "tanh", "tanh", None, itemsize=4)
    assert not pallas_kernels.lstm_supported(8, 512, "relu", "tanh", "tanh", None)
    assert not pallas_kernels.lstm_supported(
        8, 512, "sigmoid", "tanh", "tanh", jnp.zeros((3 * 512,)))
    # GRU window (round 3, hand-written bwd kernel): wins everywhere
    # measured except the H=384 dip; f32 at H=1280 busts the VMEM budget
    assert pallas_kernels.gru_supported(8, 512, "sigmoid", "tanh")
    assert pallas_kernels.gru_supported(128, 1280, "sigmoid", "tanh")
    assert not pallas_kernels.gru_supported(8, 384, "sigmoid", "tanh")
    assert not pallas_kernels.gru_supported(256, 1280, "sigmoid", "tanh")
    assert not pallas_kernels.gru_supported(128, 1280, "sigmoid", "tanh",
                                            itemsize=4)


def test_gru_fused_grads_match_scan():
    """The hand-written reverse-time GRU backward kernel (round 3 — it
    replaced the scan-replay VJP) must match the scan's gradients."""
    rng = np.random.RandomState(2)
    x = jnp.asarray(rng.randn(T, B, 3 * H).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(H, 3 * H).astype(np.float32) * 0.1)
    mask = _mask([5, 2, 4, 5, 3, 5, 1, 5])

    def loss_f(x, w):
        h, hT = pallas_kernels.gru_fused(x, mask, w)
        return jnp.sum(h**2) + jnp.sum(hT * hT)

    def loss_s(x, w):
        h, hT = rnn_ops.gru_scan(x, mask, w, None)
        return jnp.sum(h**2) + jnp.sum(hT * hT)

    gx_f, gw_f = jax.grad(loss_f, argnums=(0, 1))(x, w)
    gx_s, gw_s = jax.grad(loss_s, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(gx_f, gx_s, atol=1e-4)
    np.testing.assert_allclose(gw_f, gw_s, atol=1e-4)


def test_gru_fused_reverse_grads_match_scan():
    rng = np.random.RandomState(3)
    x = jnp.asarray(rng.randn(T, B, 3 * H).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(H, 3 * H).astype(np.float32) * 0.1)
    mask = _mask([5, 2, 4, 5, 3, 5, 1, 5])

    def loss_f(x, w):
        h, hT = pallas_kernels.gru_fused(x, mask, w, reverse=True)
        return jnp.sum(h**2) + jnp.sum(hT)

    def loss_s(x, w):
        h, hT = rnn_ops.gru_scan(x, mask, w, None, reverse=True)
        return jnp.sum(h**2) + jnp.sum(hT)

    gx_f, gw_f = jax.grad(loss_f, argnums=(0, 1))(x, w)
    gx_s, gw_s = jax.grad(loss_s, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(gx_f, gx_s, atol=1e-4)
    np.testing.assert_allclose(gw_f, gw_s, atol=1e-4)


@pytest.mark.parametrize("cell", ["lstm", "gru"])
def test_outer_dw_path_matches_fused_dw(cell, monkeypatch):
    """Past _*_FUSED_DW_MAX_H the backward drops the VMEM dW accumulator
    and computes dW as a batched einsum over the emitted dgates; force the
    threshold down so the H=128 case exercises that path and compare
    against the fused-accumulator gradients."""
    rng = np.random.RandomState(4)
    G = 4 if cell == "lstm" else 3
    x = jnp.asarray(rng.randn(T, B, G * H).astype(np.float32) * 0.1)
    w = jnp.asarray(rng.randn(H, G * H).astype(np.float32) * 0.1)
    mask = _mask([5, 2, 4, 5, 3, 5, 1, 5])
    fn = pallas_kernels.lstm_fused if cell == "lstm" else pallas_kernels.gru_fused

    def loss(x, w):
        h, last = fn(x, mask, w)
        return jnp.sum(h**2)

    gx_fused, gw_fused = jax.grad(loss, argnums=(0, 1))(x, w)
    monkeypatch.setattr(
        pallas_kernels, f"_{cell.upper()}_FUSED_DW_MAX_H", H - 1)
    gx_outer, gw_outer = jax.grad(loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(gx_outer, gx_fused, atol=1e-5)
    np.testing.assert_allclose(gw_outer, gw_fused, atol=1e-4)
