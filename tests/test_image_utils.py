"""Image utility tests (reference: python/paddle/v2/image.py:111-290)."""

import io

import numpy as np
import pytest

from paddle_tpu.data import image as pimg


def _im(h=8, w=12, c=3):
    rng = np.random.RandomState(0)
    return rng.randint(0, 256, (h, w, c), dtype=np.uint8)


def test_resize_short_aspect():
    im = _im(8, 12)
    out = pimg.resize_short(im, 16)
    assert out.shape == (16, 24, 3)  # short edge 8 → 16, aspect kept
    out2 = pimg.resize_short(_im(12, 8), 16)
    assert out2.shape == (24, 16, 3)


def test_resize_matches_pil_bilinear_upscale():
    """Upscale oracle: PIL BILINEAR == pure 2-tap bilinear when enlarging
    (downscale PIL area-averages/antialiases — a different, also valid,
    filter, so only structural checks apply there)."""
    from PIL import Image

    im = _im(8, 8)
    ours = pimg._bilinear_resize(im, 16, 16).astype(np.float32)
    ref = np.asarray(
        Image.fromarray(im).resize((16, 16), Image.BILINEAR), np.float32
    )
    assert np.abs(ours - ref).max() <= 2.0  # rounding differences only
    # downscale: right shape/dtype/range, and a constant image is exact
    const = np.full((16, 16, 3), 77, np.uint8)
    down = pimg._bilinear_resize(const, 7, 5)
    assert down.shape == (7, 5, 3) and down.dtype == np.uint8
    np.testing.assert_array_equal(down, 77)


def test_crops_and_flip():
    im = _im(10, 10)
    cc = pimg.center_crop(im, 4)
    np.testing.assert_array_equal(cc, im[3:7, 3:7])
    rc = pimg.random_crop(im, 4, rng=np.random.RandomState(3))
    assert rc.shape == (4, 4, 3)
    np.testing.assert_array_equal(pimg.left_right_flip(im), im[:, ::-1])
    np.testing.assert_array_equal(pimg.to_chw(im), im.transpose(2, 0, 1))


def test_simple_transform_train_and_test():
    im = _im(40, 60)
    tr = pimg.simple_transform(im, 32, 24, is_train=True,
                               rng=np.random.RandomState(0))
    te = pimg.simple_transform(im, 32, 24, is_train=False,
                               mean=[1.0, 2.0, 3.0])
    assert tr.shape == (3, 24, 24) and tr.dtype == np.float32
    assert te.shape == (3, 24, 24)
    # mean subtraction is per channel
    te0 = pimg.simple_transform(im, 32, 24, is_train=False)
    np.testing.assert_allclose(te0[0] - 1.0, te[0], atol=1e-5)
    np.testing.assert_allclose(te0[2] - 3.0, te[2], atol=1e-5)


def test_batch_images_from_tar_roundtrip(tmp_path):
    """Reference image.py:48-109 contract: tar → batch files + meta list,
    idempotent; batch_reader yields decoded (image, label) samples."""
    import tarfile

    from PIL import Image

    tar_path = str(tmp_path / "imgs.tar")
    imgs = {}
    with tarfile.open(tar_path, "w") as tf:
        for i in range(5):
            im = _im(6, 6)
            imgs[f"img_{i}.png"] = im
            buf = io.BytesIO()
            Image.fromarray(im).save(buf, format="PNG")
            buf.seek(0)
            info = tarfile.TarInfo(f"img_{i}.png")
            info.size = len(buf.getvalue())
            tf.addfile(info, buf)
    img2label = {f"img_{i}.png": i % 2 for i in range(5)}
    meta = pimg.batch_images_from_tar(tar_path, "train", img2label,
                                      num_per_batch=2)
    assert meta == pimg.batch_images_from_tar(tar_path, "train", img2label)
    samples = list(pimg.batch_reader(meta)())
    assert len(samples) == 5
    labels = sorted(int(lbl) for _, lbl in samples)
    assert labels == [0, 0, 0, 1, 1]
    for im, _ in samples:
        assert im.shape == (6, 6, 3)


def test_load_image_bytes_roundtrip():
    from PIL import Image

    im = _im(9, 7)
    buf = io.BytesIO()
    Image.fromarray(im).save(buf, format="PNG")
    out = pimg.load_image_bytes(buf.getvalue())
    np.testing.assert_array_equal(out, im)
    gray = pimg.load_image_bytes(buf.getvalue(), is_color=False)
    assert gray.shape == (9, 7)
