"""paddle_tpu.quant: post-training int8 quantization fast path.

The contract under test (ISSUE 15 acceptance): calibration is
deterministic (same samples → byte-identical scales, which is what lets
the meta.json scales digest double as a staleness check), the converted
artifact round-trips save/load bit-identically, mixed programs report
every skipped site loudly, quantized outputs stay within a bounded
delta of fp32, every tune-space candidate the int8 family emits is
legal by its own model, a quantized artifact serves through the
bucketed engine with zero post-warmup compiles, and a tampered
artifact (program or payload edited after export) fails LOUDLY at load
instead of serving garbage with stale scales.

Plus the zero-cost lint (the test_obs pattern extended to the quant
hot path): the dispatch-path functions must never recompute scales,
touch numpy, or host-sync — scales are convert-time artifacts.
"""

import ast
import json
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import amp, quant
from paddle_tpu.io import QuantMetaError
from paddle_tpu.quant.convert import SCALE_SUFFIX
from paddle_tpu.ops import quant_kernels as qk
from paddle_tpu.serving import BucketPolicy, ServingEngine
from paddle_tpu.tune import space as tune_space

# ---------------------------------------------------------------- fixtures --


def _build_mlp(dirname, in_dim=16, hidden=32, out_dim=8, seed=5):
    """Seeded 3-matmul MLP saved as an fp32 inference artifact."""
    pt.reset()
    pt.default_startup_program().random_seed = seed
    x = pt.layers.data("x", shape=[in_dim])
    h1 = pt.layers.fc(x, size=hidden, act="relu", name="tq_fc1")
    h2 = pt.layers.fc(h1, size=hidden, act="relu", name="tq_fc2")
    pred = pt.layers.fc(h2, size=out_dim, name="tq_fc3")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(dirname, ["x"], [pred])
    return exe


def _samples(n=4, batch=4, in_dim=16, seed=0):
    rng = np.random.RandomState(seed)
    return [{"x": rng.standard_normal((batch, in_dim))
             .astype(np.float32)} for _ in range(n)]


def _load_convert(model_dir, samples=None, **kw):
    """fp artifact → (program, feeds, fetches, scope, exe, report)."""
    scope = pt.Scope()
    exe = pt.Executor()
    program, feeds, fetches = pt.io.load_inference_model(model_dir,
                                                         scope=scope)
    samples = samples or _samples()
    calib = quant.calibrate(program, samples, scope=scope, exe=exe)
    report = quant.convert(program, scope=scope, calib=calib,
                           check_feed=samples[0], fetch_list=fetches,
                           exe=exe, **kw)
    return program, feeds, fetches, scope, exe, report


@pytest.fixture
def mlp_dir(tmp_path):
    d = str(tmp_path / "fp32")
    _build_mlp(d)
    return d


@pytest.fixture(autouse=True)
def _fresh_quant_stats():
    quant.reset_stats()
    yield
    quant.reset_stats()


# ----------------------------------------------------- precision policy ----


def test_precision_policy_one_table():
    """Satellite 1: ONE policy table drives both amp exclusion and
    quant eligibility — softmax/batch_norm can never be quantized nor
    amp-downcast, matmuls are both."""
    assert amp.precision_policy("softmax") == "high"
    assert amp.precision_policy("batch_norm") == "high"
    assert amp.precision_policy("mul") == "low"
    assert amp.precision_policy("relu") == "follow"
    assert amp.QUANTIZABLE_OPS <= amp.LOW_PRECISION_OPS
    assert not (amp.QUANTIZABLE_OPS & amp.HIGH_PRECISION_OPS)


# --------------------------------------------------------- calibration ----


def test_calibration_deterministic(mlp_dir):
    """Same samples → byte-identical ranges (twice over fresh loads,
    the property the scales digest depends on)."""
    ranges = []
    for _ in range(2):
        scope = pt.Scope()
        program, _, _ = pt.io.load_inference_model(mlp_dir, scope=scope)
        calib = quant.calibrate(program, _samples(), scope=scope)
        assert calib.sample_count == 4
        ranges.append(calib.act_ranges)
    assert ranges[0] == ranges[1]
    # one range per quantizable site's activation, all observed > 0
    assert len(ranges[0]) == 3
    assert all(v > 0 for v in ranges[0].values())


def test_calibrate_needs_samples(mlp_dir):
    scope = pt.Scope()
    program, _, _ = pt.io.load_inference_model(mlp_dir, scope=scope)
    with pytest.raises(ValueError, match="at least one sample"):
        quant.calibrate(program, [], scope=scope)


# ------------------------------------------------------------- convert ----


def test_convert_save_load_bit_identical(mlp_dir, tmp_path):
    """int8 payloads and f32 scales survive save→load byte-for-byte,
    and the reloaded program serves the exact same outputs."""
    program, feeds, fetches, scope, exe, report = _load_convert(mlp_dir)
    assert len(report.quantized) == 3 and not report.skipped
    q_dir = str(tmp_path / "int8")
    pt.io.save_inference_model(q_dir, feeds, fetches,
                               main_program=program, scope=scope)
    scope2 = pt.Scope()
    p2, _, t2 = pt.io.load_inference_model(q_dir, scope=scope2)
    for site in report.quantized:
        w1, w2 = scope.get(site["w"]), scope2.get(site["w"])
        assert np.asarray(w1).dtype == np.int8
        np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))
        sname = site["w"] + SCALE_SUFFIX
        np.testing.assert_array_equal(np.asarray(scope.get(sname)),
                                      np.asarray(scope2.get(sname)))
    feed = _samples(1, seed=9)[0]
    out1 = exe.run(program, feed=feed, fetch_list=fetches, scope=scope)
    out2 = exe.run(p2, feed=feed, fetch_list=t2, scope=scope2)
    np.testing.assert_array_equal(np.asarray(out1[0]),
                                  np.asarray(out2[0]))
    assert p2._quant_meta["mode"] == "int8"
    assert p2._quant_meta["sites"] == 3


def test_convert_accuracy_bounded(mlp_dir):
    """Per-channel int8 on a seeded MLP: output delta vs fp32 stays
    within 5% of the fp32 output range on a held-out feed."""
    program, _, fetches, scope, exe, report = _load_convert(mlp_dir)
    s3 = pt.Scope()
    p3, _, t3 = pt.io.load_inference_model(mlp_dir, scope=s3)
    feed = _samples(1, seed=123)[0]
    out_q = np.asarray(exe.run(program, feed=feed, fetch_list=fetches,
                               scope=scope)[0], np.float32)
    out_fp = np.asarray(exe.run(p3, feed=feed, fetch_list=t3,
                                scope=s3)[0], np.float32)
    delta = float(np.max(np.abs(out_q - out_fp)))
    assert delta <= 0.05 * float(np.max(np.abs(out_fp))), delta
    # the convert-time self-check recorded a delta of the same order
    assert report.accuracy_delta is not None
    assert report.accuracy_delta < 1.0


def test_mixed_program_fallback_report(tmp_path):
    """A site whose activation calibrates to absmax 0 (dead input on
    the sample feed) stays fp and the report says so LOUDLY; the rest
    of the program still quantizes."""
    pt.reset()
    pt.default_startup_program().random_seed = 7
    x = pt.layers.data("x", shape=[8])
    live = pt.layers.fc(x, size=16, act="relu", name="mx_live")
    dead_in = pt.layers.scale(x, scale=0.0)  # always-zero activation
    dead = pt.layers.fc(dead_in, size=16, name="mx_dead")
    pred = pt.layers.fc(pt.layers.elementwise_add(live, dead), size=4,
                        name="mx_out")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    d = str(tmp_path / "mixed")
    pt.io.save_inference_model(d, ["x"], [pred])

    program, feeds, fetches, scope, exe, report = _load_convert(
        d, samples=_samples(2, in_dim=8))
    assert len(report.quantized) == 2  # live + output matmul
    assert len(report.skipped) == 1
    assert "absmax 0" in report.skipped[0]["reason"]
    text = report.summary()
    assert "LEFT AT HIGHER PRECISION" in text
    assert "mixed-precision" in text
    # skipped site kept its fp op type
    types = [op.type for b in program.blocks for op in b.ops]
    assert types.count("quantized_mul") == 2
    assert types.count("mul") == 1
    # sidecar carries the skip count through save
    assert report.meta()["skipped"] == 1


def test_convert_nothing_quantizable_raises():
    """An all-fp program (no persistable 2-D weights) is an operator
    error, not a silent no-op."""
    pt.reset()
    x = pt.layers.data("x", shape=[4])
    pred = pt.layers.relu(x)
    prog = pt.default_main_program()
    calib = quant.CalibrationResult({}, 1)
    with pytest.raises(ValueError, match="no quantizable matmul"):
        quant.convert(prog, scope=pt.global_scope(), calib=calib)


def test_convert_rejects_unknown_mode(mlp_dir):
    scope = pt.Scope()
    program, _, _ = pt.io.load_inference_model(mlp_dir, scope=scope)
    calib = quant.calibrate(program, _samples(1), scope=scope)
    with pytest.raises(ValueError, match="unsupported quant mode"):
        quant.convert(program, scope=scope, calib=calib, mode="int4")


# ------------------------------------------------------- stale sidecar ----


def test_stale_program_fails_loudly(mlp_dir, tmp_path):
    """Satellite 2: editing program.json after export breaks the
    fingerprint → QuantMetaError at load, BEFORE anything serves."""
    program, feeds, fetches, scope, _, _ = _load_convert(mlp_dir)
    q_dir = str(tmp_path / "int8")
    pt.io.save_inference_model(q_dir, feeds, fetches,
                               main_program=program, scope=scope)
    p = os.path.join(q_dir, "program.json")
    with open(p) as f:
        d = json.load(f)
    for op in d["blocks"][0]["ops"]:
        if op["type"] == "quantized_mul":
            op["attrs"]["x_scale"] *= 2.0  # "retuned" by hand
            break
    with open(p, "w") as f:
        json.dump(d, f)
    with pytest.raises(QuantMetaError, match="stale"):
        pt.io.load_inference_model(q_dir, scope=pt.Scope())


def test_tampered_scales_fail_loudly(mlp_dir, tmp_path):
    """Swapping the int8/scale payload after export breaks the scales
    digest → QuantMetaError naming the mismatch."""
    program, feeds, fetches, scope, _, report = _load_convert(mlp_dir)
    q_dir = str(tmp_path / "int8")
    pt.io.save_inference_model(q_dir, feeds, fetches,
                               main_program=program, scope=scope)
    p = os.path.join(q_dir, "params.npz")
    payload = dict(np.load(p))
    sname = report.quantized[0]["w"] + "@quant_scale"
    payload[sname] = payload[sname] * 1.5
    np.savez(p, **payload)
    with pytest.raises(QuantMetaError, match="digest"):
        pt.io.load_inference_model(q_dir, scope=pt.Scope())


# ---------------------------------------------------------- tune space ----


def test_quant_tune_space_legality_property():
    """Every candidate the int8 family emits passes its own legality
    model AND config_legal membership (the interpolation gate); the
    default is always a member; tiles respect int8's (32,128) minimum
    unless they span the whole dim."""
    fam = tune_space.FAMILIES["quant_matmul"]
    shapes = [(1, 16, 8), (4, 64, 128), (8, 512, 1024), (32, 128, 96),
              (128, 1024, 2048), (256, 2048, 256), (7, 33, 130)]
    for M, K, N in shapes:
        params = fam.normalize({"M": M, "K": K, "N": N}, "int8")
        cands = fam.candidates(params)
        assert cands, (M, K, N)
        default = fam.default(params)
        assert default in cands, (M, K, N, default)
        for cfg in cands:
            bm, bn = cfg["block_m"], cfg["block_n"]
            assert M % bm == 0 and N % bn == 0, (params, cfg)
            assert bm % 32 == 0 or bm == M, (params, cfg)
            assert bn % 128 == 0 or bn == N, (params, cfg)
            assert tune_space.quant_matmul_legal(bm, bn, M, K, N)
            assert tune_space.config_legal(
                "quant_matmul", {"M": M, "K": K, "N": N}, "int8", cfg)
        assert not tune_space.config_legal(
            "quant_matmul", {"M": M, "K": K, "N": N}, "int8",
            {"block_m": M + 1, "block_n": N})


def test_quant_case_exact_all_candidates():
    """Integer contraction: every candidate tile must be EXACT vs the
    reference lowering (tol=0.0 — a fast-but-wrong tile never wins)."""
    from paddle_tpu.tune import harness

    fam = tune_space.FAMILIES["quant_matmul"]
    params = fam.normalize({"M": 64, "K": 32, "N": 256}, "int8")
    case = fam.make_case(params, "int8")
    assert case.tol == 0.0
    ref = case.reference()
    for cfg in fam.candidates(params):
        thunk = case.make(cfg)
        assert harness._numerics_ok(thunk(), ref, 0.0), cfg


def test_quant_dtype_rejected_for_other_families():
    """int8 is a quant_matmul dtype, not a blanket one — nothing stops
    normalize() on other families, but the space's DTYPES gate accepts
    it (tune CLI parity)."""
    assert "int8" in tune_space.DTYPES
    params = tune_space.FAMILIES["quant_matmul"].normalize(
        {"M": 8, "K": 8, "N": 8}, "int8")
    assert params["dtype"] == "int8"
    with pytest.raises(ValueError, match="dtype"):
        tune_space.FAMILIES["quant_matmul"].normalize(
            {"M": 8, "K": 8, "N": 8}, "fp16")


# -------------------------------------------------------------- serving ----


def test_engine_buckets_and_zero_compile_warmup(mlp_dir, tmp_path):
    """A quantized artifact through the bucketed engine: warmup
    pre-compiles every bucket, traffic is then 100% cache hits, and
    bucket padding slices away bit-exactly vs the exact-shape path."""
    program, feeds, fetches, scope, _, _ = _load_convert(mlp_dir)
    q_dir = str(tmp_path / "int8")
    pt.io.save_inference_model(q_dir, feeds, fetches,
                               main_program=program, scope=scope)
    eng = ServingEngine(q_dir, policy=BucketPolicy(max_batch_size=8),
                        model_name="tq", quantize="int8")
    oracle = ServingEngine(q_dir, model_name="tq_oracle")
    n = eng.warmup()
    assert n == len(eng.policy.batch_buckets) == eng.compiled_programs()
    assert eng.check_tuned_table()
    before = eng.exe.cache_stats["misses"]
    rng = np.random.RandomState(3)
    for k in rng.randint(1, 9, size=12):
        xv = rng.standard_normal((k, 16)).astype(np.float32)
        got = eng.predict({"x": xv})[0]
        want = oracle.predict({"x": xv}, bucketed=False)[0]
        assert got.shape[0] == k
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert eng.exe.cache_stats["misses"] == before, \
        "quantized traffic recompiled after warmup"
    # the engine advertises the artifact's quant footprint
    s = eng.stats()
    assert s["quant"]["mode"] == "int8" and s["quant"]["sites"] == 3


def test_engine_tune_cases_cover_quant_family(mlp_dir, tmp_path):
    """Satellite 6: decode_tune_cases / tune_coverage name the int8
    family per bucket, so check_tuned_table coverage counts quantized
    matmuls like any other kernel."""
    program, feeds, fetches, scope, _, _ = _load_convert(mlp_dir)
    q_dir = str(tmp_path / "int8")
    pt.io.save_inference_model(q_dir, feeds, fetches,
                               main_program=program, scope=scope)
    eng = ServingEngine(q_dir, policy=BucketPolicy(batch_buckets=(2, 4)),
                        quantize="int8")
    cases = [c for c in eng.decode_tune_cases()
             if c["family"] == "quant_matmul"]
    # 3 sites x 2 buckets
    assert len(cases) == 6
    assert {c["params"]["M"] for c in cases} == {2, 4}
    assert all(c["dtype"] == "int8" for c in cases)
    cov = eng.tune_coverage()
    assert any(c["family"] == "quant_matmul" and c["dtype"] == "int8"
               for c in cov)


def test_engine_quantize_knob_validation(mlp_dir, tmp_path):
    """quantize='int8' on an fp artifact fails loudly (pointing at the
    quant CLI); unknown modes fail; a quantized artifact also serves
    with NO knob (it's just a program)."""
    with pytest.raises(ValueError, match="paddle_tpu quant"):
        ServingEngine(mlp_dir, quantize="int8")
    with pytest.raises(ValueError, match="int8"):
        ServingEngine(mlp_dir, quantize="int4")
    program, feeds, fetches, scope, _, _ = _load_convert(mlp_dir)
    q_dir = str(tmp_path / "int8")
    pt.io.save_inference_model(q_dir, feeds, fetches,
                               main_program=program, scope=scope)
    eng = ServingEngine(q_dir)  # no knob: serves quantized anyway
    out = eng.predict({"x": _samples(1)[0]["x"]})
    assert np.asarray(out[0]).shape == (4, 8)


def test_quant_obs_gauges(mlp_dir):
    """pt_quant_* gauges appear in the unified registry after a convert
    (and not before — collector emits nothing when inactive)."""
    from paddle_tpu.obs.metrics import registry

    assert "pt_quant_sites_quantized" not in registry().render()
    _load_convert(mlp_dir)
    text = registry().render()
    assert "pt_quant_sites_quantized 3" in text
    assert "pt_quant_bytes_saved" in text
    assert "pt_quant_accuracy_delta" in text


# ------------------------------------------------ lint: hot path is cold ----

# dispatch-path functions of the quant fast path: nothing in them may
# recompute a scale (quantize_weight/act_scale are convert-time ONLY),
# call into numpy (host round-trip inside a traced kernel), or
# host-sync (.item()/.tolist()/np.asarray on traced values)
_QUANT_HOT_FNS = ("quantized_mul_kernel", "quantized_matmul_kernel",
                  "quant_matmul", "_quantize_act", "_dequant_epilogue")
_BANNED_CALLS = {"quantize_weight", "act_scale", "item", "tolist",
                 "block_until_ready"}
# np.* is banned on the hot path except static host-shape arithmetic
_NP_ALLOWED = {"prod"}


def test_quant_hot_path_zero_cost_lint():
    """Satellite 5: the AST lint of test_obs extended to the quant
    dispatch path — no per-call scale recompute, no numpy, no host
    syncs inside the traced kernels."""
    import paddle_tpu.ops.quant_kernels as mod

    with open(mod.__file__) as f:
        tree = ast.parse(f.read())
    found = set()
    for name in _QUANT_HOT_FNS:
        fns = [n for n in ast.walk(tree)
               if isinstance(n, ast.FunctionDef) and n.name == name]
        assert fns, f"{name} not found (lint is stale)"
        found.add(name)
        for fn in fns:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f_ = node.func
                cname = f_.id if isinstance(f_, ast.Name) else (
                    f_.attr if isinstance(f_, ast.Attribute) else None)
                assert cname not in _BANNED_CALLS, (
                    f"{name}:{node.lineno} calls {cname}() on the quant "
                    "dispatch path — scales are convert-time artifacts, "
                    "never recomputed or host-synced per call")
                if (isinstance(f_, ast.Attribute)
                        and isinstance(f_.value, ast.Name)
                        and f_.value.id == "np"):
                    assert f_.attr in _NP_ALLOWED, (
                        f"{name}:{node.lineno} calls np.{f_.attr}() in "
                        "a traced quant kernel — host numpy on the hot "
                        "path")
    assert found == set(_QUANT_HOT_FNS)
