"""recurrent_group / StaticRNN tests.

Reference analogues: gserver/tests/test_RecurrentGradientMachine.cpp and
test_RecurrentLayer.cpp — a hand-built step network must match a plain
per-sequence loop (the dual-implementation oracle, SURVEY.md §4.2), carry
memories across frames, boot memories from another layer's output, and
train (grads through the frames into shared parameters).
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.lod import LoDArray


def _lod(seqs, dtype=np.float32, **kw):
    return LoDArray.from_sequences([np.asarray(s, dtype) for s in seqs], **kw)


def _np_rnn(seqs, w, b, reverse=False):
    """Plain-python oracle: h_t = tanh([x_t, h_{t-1}] @ w + b)."""
    outs = []
    H = w.shape[1]
    for s in seqs:
        s = list(s)[::-1] if reverse else list(s)
        h = np.zeros((H,), np.float32)
        hs = []
        for x in s:
            h = np.tanh(np.concatenate([np.asarray(x, np.float32), h]) @ w + b)
            hs.append(h)
        outs.append(hs[::-1] if reverse else hs)
    return outs


def _build_group(D, H, reverse=False):
    x = pt.layers.data("x", shape=[-1, D], lod_level=1, append_batch_size=False)
    rnn = pt.layers.RecurrentGroup(is_reverse=reverse, max_len=8)
    with rnn.step():
        x_t = rnn.step_input(x)
        h_prev = rnn.memory(shape=[H])
        h = pt.layers.fc(
            pt.layers.concat([x_t, h_prev], axis=1), size=H, act="tanh"
        )
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    return rnn


@pytest.mark.parametrize("reverse", [False, True])
def test_recurrent_group_matches_numpy(reverse):
    D, H = 3, 4
    rnn = _build_group(D, H, reverse)
    out = rnn()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    seqs = [rng.randn(5, D), rng.randn(2, D), rng.randn(3, D)]
    (got,) = exe.run(
        feed={"x": _lod(seqs, bucket=16)}, fetch_list=[out], return_numpy=False
    )
    scope = pt.global_scope()
    params = sorted(
        v.name for v in pt.default_main_program().parameters()
    )
    w = np.asarray(scope.get([p for p in params if ".w" in p][0]))
    b = np.asarray(scope.get([p for p in params if ".b" in p][0]))
    want = _np_rnn(seqs, w, b, reverse)
    data = np.asarray(got.data)
    off = 0
    for s_want in want:
        for h_want in s_want:
            np.testing.assert_allclose(data[off], h_want, atol=1e-5)
            off += 1


def test_final_memory_is_last_state():
    D, H = 2, 3
    rnn = _build_group(D, H)
    out = rnn()
    final = rnn.get_final_memory(0)
    last = pt.layers.sequence_last_step(out)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(1)
    seqs = [rng.randn(4, D), rng.randn(1, D)]
    fin, lst = exe.run(
        feed={"x": _lod(seqs, bucket=8)}, fetch_list=[final, last]
    )
    np.testing.assert_allclose(fin[:2], lst[:2], atol=1e-6)


def test_memory_boot_from_variable():
    """Decoder-style: memory booted from a dense per-sequence vector."""
    D, H = 2, 3
    x = pt.layers.data("x", shape=[-1, D], lod_level=1, append_batch_size=False)
    boot = pt.layers.data("boot", shape=[-1, H], append_batch_size=False)
    rnn = pt.layers.RecurrentGroup(max_len=8)
    with rnn.step():
        x_t = rnn.step_input(x)
        h_prev = rnn.memory(init=boot)
        h = pt.layers.fc(
            pt.layers.concat([x_t, h_prev], axis=1), size=H, act="tanh"
        )
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    out = rnn()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(2)
    seqs = [rng.randn(3, D), rng.randn(2, D)]
    lod = _lod(seqs, bucket=8)
    boot_v = rng.randn(lod.max_seqs, H).astype(np.float32)
    (got,) = exe.run(
        feed={"x": lod, "boot": boot_v}, fetch_list=[out], return_numpy=False
    )
    scope = pt.global_scope()
    params = sorted(v.name for v in pt.default_main_program().parameters())
    w = np.asarray(scope.get([p for p in params if ".w" in p][0]))
    b = np.asarray(scope.get([p for p in params if ".b" in p][0]))
    data = np.asarray(got.data)
    off = 0
    for i, s in enumerate(seqs):
        h = boot_v[i]
        for xrow in s:
            h = np.tanh(np.concatenate([xrow.astype(np.float32), h]) @ w + b)
            np.testing.assert_allclose(data[off], h, atol=1e-5)
            off += 1


def test_functional_wrapper():
    D, H = 2, 3
    x = pt.layers.data("x", shape=[-1, D], lod_level=1, append_batch_size=False)

    def step(x_t, rnn):
        h_prev = rnn.memory(shape=[H])
        h = pt.layers.fc(
            pt.layers.concat([x_t, h_prev], axis=1), size=H, act="tanh"
        )
        rnn.update_memory(h_prev, h)
        return h

    out = pt.layers.recurrent_group(step, x, max_len=8)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    (got,) = exe.run(
        feed={"x": _lod([np.ones((2, D))], bucket=8)},
        fetch_list=[out],
        return_numpy=False,
    )
    assert np.asarray(got.data).shape[1] == H


def test_int_memory_dtype_respected():
    """A boot-less memory with dtype=int32 carries integers (e.g. a step

    counter in a decoder)."""
    x = pt.layers.data("x", shape=[-1, 2], lod_level=1, append_batch_size=False)
    rnn = pt.layers.RecurrentGroup(max_len=8)
    with rnn.step():
        x_t = rnn.step_input(x)
        cnt_prev = rnn.memory(shape=[1], dtype=np.int32)
        cnt = pt.layers.elementwise_add(
            cnt_prev, pt.layers.fill_constant([1, 1], np.int32, 1)
        )
        rnn.update_memory(cnt_prev, cnt)
        rnn.step_output(pt.layers.cast(cnt, np.float32))
    out = rnn()
    final = rnn.get_final_memory(0)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    (fin,) = exe.run(
        feed={"x": _lod([np.zeros((3, 2)), np.zeros((1, 2))], bucket=8)},
        fetch_list=[final],
    )
    assert fin.dtype == np.int32
    assert fin[0, 0] == 3 and fin[1, 0] == 1


def test_recurrent_group_trains():
    """Grads flow through the scanned frames into the shared parameters."""
    D, H = 4, 8
    x = pt.layers.data("x", shape=[-1, D], lod_level=1, append_batch_size=False)
    label = pt.layers.data("label", shape=[-1, 1], dtype=np.int32,
                           append_batch_size=False)
    rnn = _build_group(D, H)
    out = rnn()
    last = pt.layers.sequence_last_step(out)
    logits = pt.layers.fc(last, size=2)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(3)
    # class = sign of the mean of the sequence's first feature
    seqs = [rng.randn(rng.randint(2, 6), D) for _ in range(8)]
    labels = np.array(
        [[int(s[:, 0].mean() > 0)] for s in seqs], np.int32
    )
    lab = np.zeros((8, 1), np.int32)
    lab[: len(labels)] = labels
    lod = _lod(seqs, bucket=64, max_seqs=8)
    losses = []
    for _ in range(30):
        (l,) = exe.run(
            feed={"x": lod, "label": lab},
            fetch_list=[loss],
        )
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.7, losses[::10]
