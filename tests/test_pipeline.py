"""Micro-batch pipeline-parallel executor (ISSUE 14, paddle_tpu/pipeline).

The load-bearing claim is the determinism contract: for a fixed
microbatch count M, the staged GPipe schedule produces BIT-IDENTICAL
parameters to the unstaged run for every stage count K — masked bubble
cells add exact 0.0, the reverse scan drains microbatch gradients in a
K-invariant order, and the partitioner snaps automatic cuts to the
narrowest boundary so a cut never forces a cotangent across the scan
carry mid-fusion (the transformer A/B below is the regression test for
exactly that failure, observed before _narrow_cuts existed).
"""

import ast
import logging
import os

import numpy as np
import pytest

import jax

import paddle_tpu as pt
from paddle_tpu import models
from paddle_tpu import parallel as pp
from paddle_tpu.obs import promparse
from paddle_tpu.obs.metrics import registry
from paddle_tpu.pipeline import (
    PipelineExecutor, split_program, stage_boundary,
)
from paddle_tpu.pipeline import partition as ppart


# ------------------------------------------------------------- builders --


def _mlp(depth=4, dim=16, markers=False, seed=7):
    pt.default_main_program().random_seed = seed
    pt.default_startup_program().random_seed = seed
    x = pt.layers.data("x", shape=[dim])
    y = pt.layers.data("y", shape=[1])
    h = x
    for i in range(depth):
        if markers and i in (depth // 2,):
            stage_boundary()
        h = pt.layers.fc(h, size=dim, act="relu")
    pred = pt.layers.fc(h, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.Adam(learning_rate=1e-2).minimize(loss)
    return loss


def _mlp_feed(batch=8, dim=16, seed=0):
    rng = np.random.RandomState(seed)
    return {"x": rng.randn(batch, dim).astype(np.float32),
            "y": rng.randn(batch, 1).astype(np.float32)}


def _tiny_transformer(seed=11, dim=32, depth=2, seqlen=8, vocab=50):
    pt.default_main_program().random_seed = seed
    pt.default_startup_program().random_seed = seed
    toks = pt.layers.data("toks", shape=[seqlen], dtype=np.int32)
    labels = pt.layers.data("labels", shape=[seqlen, 1], dtype=np.int32)
    logits = models.transformer_lm(toks, vocab_size=vocab, dim=dim,
                                   num_heads=1, num_layers=depth,
                                   max_len=seqlen)
    loss = pt.layers.mean(
        pt.layers.softmax_with_cross_entropy(logits, labels))
    pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    return loss


def _tfm_feed(batch=8, seqlen=8, vocab=50, seed=0):
    rng = np.random.RandomState(seed)
    return {
        "toks": rng.randint(0, vocab, (batch, seqlen)).astype(np.int32),
        "labels": rng.randint(0, vocab,
                              (batch, seqlen, 1)).astype(np.int32),
    }


def _params():
    return {n: np.asarray(pt.global_scope().get(n))
            for n in sorted(pt.global_scope().keys())
            if not n.startswith("@")}


def _step_params(build, feed, steps=2, **exe_kw):
    pt.reset()
    loss = build()
    exe = PipelineExecutor(**exe_kw)
    exe.run_startup(pt.default_startup_program())
    losses = []
    for s in range(steps):
        (l,) = exe.run(feed=feed(seed=s), fetch_list=[loss])
        losses.append(float(np.asarray(l)))
    return losses, _params()


# ------------------------------------------------------------ partition --


def test_split_auto_balanced_contract():
    _mlp(depth=6)
    staged = split_program(pt.default_main_program(), num_stages=3)
    assert len(staged.stages) == 3
    persist = {v.name for v in pt.default_main_program().persistables()}
    assert all(len(s.ops) >= 1 for s in staged.stages)
    for s in staged.stages:
        # persistables never cross a boundary; they enter via state
        assert not (set(s.out_names) & persist)
        assert set(s.state_names) <= persist
    # every intermediate boundary produces what the next stages consume
    for a, b in zip(staged.stages, staged.stages[1:]):
        assert a.out_names, "non-final stage must export its boundary"
        assert set(a.out_names) <= set(b.in_names) | {
            n for st in staged.stages[b.index:] for n in st.in_names}


def test_split_marker_cuts_win():
    _mlp(depth=4, markers=True)
    staged = split_program(pt.default_main_program(), num_stages=2)
    assert len(staged.stages) == 2
    # the marker sits before fc layer depth//2: stage 0 holds exactly
    # the ops of the first two fc layers (mul+add+relu each)
    first_types = [op.type for op in staged.stages[0].ops]
    assert first_types.count("mul") == 2


def test_split_unmarked_requires_num_stages():
    _mlp(depth=2)
    with pytest.raises(ValueError, match="num_stages"):
        split_program(pt.default_main_program())


def test_split_rejects_oversplit():
    _mlp(depth=2)
    with pytest.raises(ValueError, match="exceeds"):
        split_program(pt.default_main_program(), num_stages=10_000)


def test_split_rejects_sparse_embedding():
    toks = pt.layers.data("t", shape=[4], dtype=np.int32)
    y = pt.layers.data("y", shape=[1])
    emb = pt.layers.embedding(toks, size=[16, 8], is_sparse=True)
    pooled = pt.layers.reduce_mean(emb, dim=1)
    pred = pt.layers.fc(pooled, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    with pytest.raises(NotImplementedError, match="sparse"):
        split_program(pt.default_main_program(), num_stages=2)


def test_split_rejects_trainmode_batchnorm():
    x = pt.layers.data("x", shape=[8])
    y = pt.layers.data("y", shape=[1])
    h = pt.layers.fc(x, size=8)
    h = pt.layers.batch_norm(h)  # train mode writes running stats
    pred = pt.layers.fc(h, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    with pytest.raises(NotImplementedError, match="persistable"):
        split_program(pt.default_main_program(), num_stages=2)


def test_auto_cut_narrows_to_residual_boundary():
    """The DP balancer alone would happily cut through the middle of a
    residual block (boundary = skip tensor + mid-block tmp, width 2);
    _narrow_cuts must slide the cut to the residual stream (width 1).
    This is the partition-level guarantee behind the transformer
    bit-identity A/B below."""
    x = pt.layers.data("x", shape=[8])
    y = pt.layers.data("y", shape=[1])
    h = x
    for _ in range(4):
        b = pt.layers.fc(h, size=8, act="relu")
        b = pt.layers.fc(b, size=8)
        h = pt.layers.elementwise_add(h, b)
    pred = pt.layers.fc(h, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    staged = split_program(pt.default_main_program(), num_stages=2)
    assert len(staged.stages[0].out_names) == 1, staged.stages[0].out_names


# ------------------------------------------------- fixed-seed A/B (MLP) --


@pytest.mark.parametrize("k,schedule", [(2, "gpipe"), (4, "gpipe"),
                                        (2, "1f1b")])
def test_pipeline_bitwise_vs_unstaged_mlp(k, schedule):
    """Params after 2 fixed-seed steps are BIT-identical across stage
    counts at fixed M — the core determinism contract."""
    ref_losses, ref = _step_params(_mlp, _mlp_feed, num_stages=1,
                                   num_microbatches=4)
    losses, got = _step_params(_mlp, _mlp_feed, num_stages=k,
                               num_microbatches=4, schedule=schedule)
    assert losses == ref_losses
    assert set(got) == set(ref)
    bad = [n for n in ref if not np.array_equal(ref[n], got[n])]
    assert not bad, f"K={k} {schedule}: diverged {bad[:6]}"


def test_pipeline_bitwise_vs_unstaged_transformer_autocut():
    """Regression test for the narrowed-cut fix: the auto-balancer's
    natural cut on a transformer lands mid-fc (between a mul and its
    bias add), which reassociates the upstream backward and voids
    bitwise identity; _narrow_cuts snaps it to the residual stream.
    K=2 must match K=1 exactly, not approximately."""
    ref_losses, ref = _step_params(_tiny_transformer, _tfm_feed,
                                   steps=1, num_stages=1,
                                   num_microbatches=4)
    losses, got = _step_params(_tiny_transformer, _tfm_feed,
                               steps=1, num_stages=2, num_microbatches=4)
    assert losses == ref_losses
    bad = [n for n in ref if not np.array_equal(ref[n], got[n])]
    assert not bad, f"transformer K=2: diverged {bad[:6]}"


def test_pipeline_marker_cut_bitwise():
    ref_losses, ref = _step_params(lambda: _mlp(markers=True), _mlp_feed,
                                   num_stages=1, num_microbatches=2)
    losses, got = _step_params(lambda: _mlp(markers=True), _mlp_feed,
                               num_stages=2, num_microbatches=2)
    assert losses == ref_losses
    assert all(np.array_equal(ref[n], got[n]) for n in ref)


def test_pipeline_requires_divisible_batch():
    pt.reset()
    loss = _mlp()
    exe = PipelineExecutor(num_stages=2, num_microbatches=3)
    exe.run_startup(pt.default_startup_program())
    with pytest.raises(ValueError, match="divisible|microbatch"):
        exe.run(feed=_mlp_feed(batch=8), fetch_list=[loss])


# -------------------------------------------------- trainer integration --


def test_trainer_runs_on_pipeline_executor():
    loss = _mlp()

    def reader():
        for i in range(6):
            yield _mlp_feed(seed=i)

    t = pt.Trainer(loss, executor=PipelineExecutor(
        num_stages=2, num_microbatches=4))
    metrics = t.train(reader, num_passes=1, log_interval=3)
    assert np.isfinite(metrics["cost"])


def test_mesh_scan_window_fallback_names_pipeline(caplog):
    """Satellite 1: the scan-window fallback on mesh executors is LOUD
    and tells the user the pipeline executor is the alternative."""
    mesh = pp.mesh_from_spec("dp2")
    loss = _mlp()

    def reader():
        for i in range(2):
            yield _mlp_feed(seed=i)

    t = pt.Trainer(loss, executor=pp.ParallelExecutor(mesh))
    with caplog.at_level(logging.WARNING, logger="paddle_tpu.trainer"):
        t.train(reader, num_passes=1, scan_window=2)
    assert any("PipelineExecutor" in r.message for r in caplog.records)


# ----------------------------------------------------------------- mesh --


@pytest.mark.needs_multidevice_pp
def test_pipeline_on_pp_mesh_matches_meshless():
    _, ref = _step_params(_mlp, _mlp_feed, num_stages=2,
                          num_microbatches=4)
    pt.reset()
    loss = _mlp()
    mesh = pp.mesh_from_spec("dp2,pp2")
    exe = PipelineExecutor(num_stages=2, num_microbatches=4, mesh=mesh)
    exe.run_startup(pt.default_startup_program())
    for s in range(2):
        (l,) = exe.run(feed=_mlp_feed(seed=s), fetch_list=[loss])
    assert np.isfinite(np.asarray(l))
    got = _params()
    # GSPMD changes reduction order: close, not bitwise
    for n in ref:
        np.testing.assert_allclose(ref[n], got[n], rtol=2e-4, atol=1e-5)


@pytest.mark.needs_multidevice_pp
def test_pipeline_stage_count_must_divide_pp_axis():
    _mlp()
    mesh = pp.mesh_from_spec("dp2,pp2")
    with pytest.raises(ValueError, match="pp"):
        PipelineExecutor(num_stages=3, num_microbatches=4, mesh=mesh)


# -------------------------------------------------------------- metrics --


def test_pipeline_metrics_declared_then_live():
    """Satellite 6: series exist at 0 before the first dispatch (scrape
    never sees a missing family), then report the schedule's analytic
    bubble/occupancy after it."""
    import gc

    pt.reset()
    gc.collect()  # drop earlier tests' executors: their weakref-backed
    # collectors would otherwise still answer this scrape
    loss = _mlp()
    exe = PipelineExecutor(num_stages=4, num_microbatches=4)
    fams = promparse.parse_text(registry().render())
    assert fams["pt_pipeline_bubble_fraction"].value() == 0.0
    assert fams["pt_ckpt_reshard_total"].value() == 0.0

    exe.run_startup(pt.default_startup_program())
    exe.run(feed=_mlp_feed(), fetch_list=[loss])
    fams = promparse.parse_text(registry().render())
    np.testing.assert_allclose(
        fams["pt_pipeline_bubble_fraction"].value(), 3 / 7)
    for s in range(4):
        np.testing.assert_allclose(
            fams["pt_pipeline_stage_occupancy"].value({"stage": str(s)}),
            4 / 7)


# ----------------------------------------------------- host-sync lint --


def test_stage_schedule_hot_loop_has_no_host_syncs():
    """Satellite 5: AST lint over pipeline/schedule.py — the staged-step
    trace functions must never call a host-sync primitive (device_get /
    block_until_ready / np.asarray / .item / .tolist); one sync inside
    the tick body would serialize the whole grid per step."""
    import paddle_tpu.pipeline.schedule as sched

    src = open(sched.__file__.rstrip("c")).read()
    tree = ast.parse(src)
    hot = {"raw", "tick", "run_stage", "probe", "_staged_step"}
    banned = {"device_get", "block_until_ready", "asarray", "item",
              "tolist", "copy_to_host_async"}
    offenders = []

    class V(ast.NodeVisitor):
        def __init__(self):
            self.stack = []

        def visit_FunctionDef(self, node):
            self.stack.append(node.name)
            self.generic_visit(node)
            self.stack.pop()

        def visit_Attribute(self, node):
            if node.attr in banned and set(self.stack) & hot:
                offenders.append((self.stack[-1], node.attr, node.lineno))
            self.generic_visit(node)

    V().visit(tree)
    assert not offenders, (
        f"host syncs in the stage-schedule hot loop: {offenders}")
