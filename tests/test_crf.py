"""Linear-chain CRF vs brute-force enumeration (the grad-check-style

oracle of SURVEY §4.1 applied to the CRF: reference tests
gserver/tests/test_LinearChainCRF.cpp compare against naive loops)."""

import itertools

import numpy as np

import paddle_tpu as pt
from paddle_tpu.core.lod import LoDArray
from paddle_tpu.ops.crf_ops import crf_nll, crf_viterbi

D = 3


def _path_score(emit, labels, transition):
    start_w, end_w, trans = transition[0], transition[1], transition[2:]
    s = start_w[labels[0]] + end_w[labels[-1]]
    s += sum(emit[t, labels[t]] for t in range(len(labels)))
    s += sum(trans[labels[t - 1], labels[t]] for t in range(1, len(labels)))
    return s


def _brute(emit, transition):
    T = emit.shape[0]
    paths = list(itertools.product(range(D), repeat=T))
    scores = np.array([_path_score(emit, p, transition) for p in paths])
    log_z = np.logaddexp.reduce(scores)
    best = paths[int(np.argmax(scores))]
    return log_z, np.array(best)


def test_crf_nll_and_viterbi_match_brute_force():
    rng = np.random.RandomState(0)
    lens = [4, 2, 5]
    emits = [rng.randn(L, D).astype(np.float32) for L in lens]
    labels = [rng.randint(0, D, (L,)).astype(np.int32) for L in lens]
    transition = rng.randn(D + 2, D).astype(np.float32) * 0.5

    emission = LoDArray.from_sequences(emits, capacity=16, max_seqs=3)
    label_l = LoDArray.from_sequences(labels, capacity=16, max_seqs=3)

    nll = np.asarray(crf_nll(emission, label_l, transition))
    tags, mask = crf_viterbi(emission, transition)
    tags = np.asarray(tags)

    for i, (e, l) in enumerate(zip(emits, labels)):
        log_z, best = _brute(e, transition)
        gold = _path_score(e, l, transition)
        np.testing.assert_allclose(nll[i], log_z - gold, rtol=1e-4, atol=1e-4)
        np.testing.assert_array_equal(tags[: lens[i], i], best)


def test_crf_layer_gradcheck_converges():
    """Train emissions+transitions on a deterministic tag pattern; the

    nll must approach 0 (perfectly learnable mapping)."""
    rng = np.random.RandomState(1)
    vocab, ntag = 10, D

    def make(n=8):
        xs, ys = [], []
        for _ in range(n):
            L = rng.randint(3, 7)
            w = rng.randint(0, vocab, (L,)).astype(np.int32)
            y = (w % ntag).astype(np.int32)  # tag fully determined by word
            xs.append(w)
            ys.append(y)
        return (LoDArray.from_sequences(xs, capacity=64, max_seqs=n),
                LoDArray.from_sequences(ys, capacity=64, max_seqs=n))

    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 3
    with pt.program_guard(prog, startup):
        words = pt.layers.data("w", [-1], np.int32, lod_level=1,
                               append_batch_size=False)
        label = pt.layers.data("y", [-1], np.int32, lod_level=1,
                               append_batch_size=False)
        emb = pt.layers.embedding(words, size=[vocab, 16])
        emit = pt.layers.fc(emb, size=ntag)
        nll = pt.layers.linear_chain_crf(emit, label, param_attr="crf_w",
                                         max_len=8)
        cost = pt.layers.mean(nll)
        decoded = pt.layers.crf_decoding(emit, param_attr="crf_w", max_len=8)
        pt.optimizer.Adam(learning_rate=0.05).minimize(cost)
    exe = pt.Executor()
    exe.run(startup)
    first = None
    for i in range(60):
        x, y = make()
        (c,) = exe.run(prog, feed={"w": x, "y": y}, fetch_list=[cost])
        if first is None:
            first = float(c)
    assert float(c) < 0.1 * first, f"CRF nll {first} -> {float(c)}"

    # decode accuracy on a fresh batch
    x, y = make()
    (dec,) = exe.run(prog, feed={"w": x, "y": y}, fetch_list=[decoded],
                     return_numpy=False)
    pred = np.asarray(dec.data)[:, 0]
    mask = np.asarray(dec.seq_ids) >= 0
    truth = np.asarray(x.data) % ntag
    acc = (pred[mask] == truth[mask]).mean()
    assert acc > 0.95, f"viterbi decode acc {acc}"
