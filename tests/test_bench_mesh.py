"""BENCH_MESH smoke: the bench path (not just the dryrun path) runs
under an explicit multi-device mesh.

Reference scale-out table: benchmark/README.md:72-96 (the 4-GPU
columns). The real command for multi-chip hardware is
`BENCH_MESH=dp4,mp2 BENCH_MODEL=transformer python bench.py`; here the
same code path runs on the 8-virtual-device CPU mesh with tiny shapes —
dp batch sharding + Megatron mp (transformer_lm mp_axis) + ZeRO-sharded
optimizer state, through bench.py's own timing loop.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "BENCH_STEPS": "2",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.update(extra_env)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout.strip().splitlines()[-1])

def test_transformer_bench_under_dp_mp_mesh():
    rec = _run_bench({
        "BENCH_MODEL": "transformer", "BENCH_MESH": "dp2,mp2",
        "BENCH_BATCH": "4", "BENCH_HIDDEN": "128", "BENCH_DEPTH": "2",
        "BENCH_SEQLEN": "128",
    })
    assert rec["metric"] == \
        "transformer_lm_d128_train_tokens_per_sec_mesh_dp2,mp2"
    assert np.isfinite(rec["value"]) and rec["value"] > 0


def test_lstm_bench_under_dp_mesh():
    rec = _run_bench({
        "BENCH_MODEL": "lstm", "BENCH_MESH": "dp8",
        "BENCH_BATCH": "16", "BENCH_HIDDEN": "128", "BENCH_SEQLEN": "16",
    })
    assert rec["metric"].endswith("_mesh_dp8")
    assert np.isfinite(rec["value"]) and rec["value"] > 0


@pytest.mark.needs_shard_map
def test_lstm_bench_mesh_at_fused_in_window_shape():
    """VERDICT r4 weak #2/#5: the mesh smoke must exercise the shapes
    the fused kernels actually engage at (H=512 is in the fused-LSTM
    window; per-shard batch 32/4=8 passes eligibility), not only
    below-window toys. Dispatch-engagement itself is asserted by
    tests/test_mesh_fused_kernels.py; this proves the BENCH path (the
    multi-chip one-liner) runs them end-to-end."""
    rec = _run_bench({
        "BENCH_MODEL": "lstm", "BENCH_MESH": "dp4",
        "BENCH_BATCH": "32", "BENCH_HIDDEN": "512", "BENCH_SEQLEN": "8",
        "BENCH_AMP": "0",  # interpret-mode kernels on the CPU mesh
        "PT_FLAGS_FUSED_RNN_INTERPRET": "1",
    })
    assert rec["metric"].endswith("_mesh_dp4")
    assert np.isfinite(rec["value"]) and rec["value"] > 0


@pytest.mark.needs_shard_map
def test_nmt_bench_under_dp_mesh_fused():
    """BENCH_MESH x BENCH_MODEL=nmt — the fused Bahdanau decoder under
    a dp2 mesh through bench.py's own path (tiny eligible geometry:
    A=C=H=128, per-shard batch 8)."""
    rec = _run_bench({
        "BENCH_MODEL": "nmt", "BENCH_MESH": "dp2",
        "BENCH_BATCH": "16", "BENCH_HIDDEN": "128", "BENCH_SEQLEN": "10",
        "BENCH_AMP": "0",
        "PT_FLAGS_FUSED_ATTENTION_INTERPRET": "1",
    })
    assert rec["metric"].endswith("_mesh_dp2")
    assert np.isfinite(rec["value"]) and rec["value"] > 0


def test_mesh_rejects_non_dividing_batch():
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "BENCH_MODEL": "lstm", "BENCH_MESH": "dp8", "BENCH_BATCH": "12",
        "BENCH_HIDDEN": "128", "BENCH_SEQLEN": "8", "BENCH_STEPS": "2",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=300,
    )
    assert r.returncode != 0
    assert "does not divide" in (r.stderr + r.stdout)


def test_dp_scaling_efficiency_floor():
    """Fixed global batch, dp1 vs dp8 on the timeshared CPU mesh.
    Measured curve (benchmarks/mesh_scaling.json): dp2 0.77, dp4 0.65,
    dp8 0.44 of dp1 — the cost is 8 per-shard programs timesharing ONE
    physical core (batch 8 vs 64 amortizes per-step overhead worse),
    not the sharding machinery. The floor at 0.3 guards the
    catastrophic regression class (e.g. an accidental full replication
    would be ~8x slower, far below it), not the curve itself; the
    preparable analogue of the reference's 4-GPU table
    (benchmark/README.md:72-96) — real Nx needs real chips."""
    common = {"BENCH_MODEL": "lstm", "BENCH_BATCH": "64",
              "BENCH_HIDDEN": "256", "BENCH_SEQLEN": "16",
              "BENCH_STEPS": "6", "BENCH_AMP": "0", "BENCH_CALIBRATE": "0"}
    # best-of-2 per arm: single-shot wall-clock on the timeshared 1-core
    # box flakes under transient load (the d34af46 overlap-test lesson)
    r1 = max((_run_bench(dict(common))["value"] for _ in range(2)))
    r8 = max((_run_bench({**common, "BENCH_MESH": "dp8"})["value"]
              for _ in range(2)))
    assert r8 >= 0.3 * r1, (r1, r8)
