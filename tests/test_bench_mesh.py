"""BENCH_MESH smoke: the bench path (not just the dryrun path) runs
under an explicit multi-device mesh.

Reference scale-out table: benchmark/README.md:72-96 (the 4-GPU
columns). The real command for multi-chip hardware is
`BENCH_MESH=dp4,mp2 BENCH_MODEL=transformer python bench.py`; here the
same code path runs on the 8-virtual-device CPU mesh with tiny shapes —
dp batch sharding + Megatron mp (transformer_lm mp_axis) + ZeRO-sharded
optimizer state, through bench.py's own timing loop.
"""

import json
import os
import subprocess
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_bench(extra_env):
    env = dict(os.environ)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "BENCH_STEPS": "2",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    env.update(extra_env)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    return json.loads(r.stdout.strip().splitlines()[-1])

def test_transformer_bench_under_dp_mp_mesh():
    rec = _run_bench({
        "BENCH_MODEL": "transformer", "BENCH_MESH": "dp2,mp2",
        "BENCH_BATCH": "4", "BENCH_HIDDEN": "128", "BENCH_DEPTH": "2",
        "BENCH_SEQLEN": "128",
    })
    assert rec["metric"] == \
        "transformer_lm_d128_train_tokens_per_sec_mesh_dp2,mp2"
    assert np.isfinite(rec["value"]) and rec["value"] > 0


def test_lstm_bench_under_dp_mesh():
    rec = _run_bench({
        "BENCH_MODEL": "lstm", "BENCH_MESH": "dp8",
        "BENCH_BATCH": "16", "BENCH_HIDDEN": "128", "BENCH_SEQLEN": "16",
    })
    assert rec["metric"].endswith("_mesh_dp8")
    assert np.isfinite(rec["value"]) and rec["value"] > 0
