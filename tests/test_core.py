"""Core IR/executor tests (reference analogues: framework tests —

scope_test.cc, op_registry_test.cc, executor harness in fluid tests)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.lod import LoDArray


def test_program_structure():
    prog = pt.Program()
    b = prog.global_block()
    v = b.create_var("x", (2, 3))
    assert b.var("x") is v
    op = b.append_op("relu", inputs={"X": [v]}, outputs={"Out": ["y"]})
    assert op.type == "relu"
    assert prog.version > 0


def test_program_serialization_roundtrip():
    prog = pt.Program()
    b = prog.global_block()
    b.create_var("x", (4, 4))
    b.append_op("relu", inputs={"X": ["x"]}, outputs={"Out": ["y"]})
    d = prog.to_dict()
    p2 = pt.Program.from_dict(d)
    assert p2.global_block().ops[0].type == "relu"
    assert p2.global_block().var("x").shape == (4, 4)


def test_executor_simple_op():
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.relu(x)
    exe = pt.Executor()
    xv = np.array([[-1.0, 2.0, -3.0, 4.0]], dtype=np.float32)
    (out,) = exe.run(feed={"x": xv}, fetch_list=[y])
    np.testing.assert_allclose(out, [[0, 2, 0, 4]])


def test_executor_compile_cache():
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.scale(x, scale=2.0)
    exe = pt.Executor()
    xv = np.ones((2, 4), dtype=np.float32)
    exe.run(feed={"x": xv}, fetch_list=[y])
    n = len(exe._cache)
    exe.run(feed={"x": xv + 1}, fetch_list=[y])
    assert len(exe._cache) == n  # same shapes -> cached
    exe.run(feed={"x": np.ones((3, 4), np.float32)}, fetch_list=[y])
    assert len(exe._cache) == n + 1  # new shape bucket


def test_autodiff_matches_numeric():
    x = pt.layers.data("x", shape=[3])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1, param_attr=pt.ParamAttr(name="w"),
                        bias_attr=pt.ParamAttr(name="b"))
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.append_backward(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.randn(5, 3).astype(np.float32)
    yv = rng.randn(5, 1).astype(np.float32)
    scope = pt.global_scope()

    g_w, l0 = exe.run(feed={"x": xv, "y": yv}, fetch_list=["w@GRAD", loss])

    # finite differences on w (the reference's checkgrad oracle,
    # trainer/Trainer.cpp:303)
    w0 = np.asarray(scope.get("w")).copy()
    eps = 1e-3
    num = np.zeros_like(w0)
    for i in range(w0.shape[0]):
        for j in range(w0.shape[1]):
            for s, sign in ((eps, 1), (-eps, -1)):
                w = w0.copy()
                w[i, j] += s
                scope.set("w", w)
                (l,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
                num[i, j] += sign * float(l)
    num /= 2 * eps
    np.testing.assert_allclose(g_w, num, rtol=1e-2, atol=1e-3)


def test_lod_array_roundtrip():
    seqs = [np.arange(3, dtype=np.float32).reshape(3, 1),
            np.arange(5, dtype=np.float32).reshape(5, 1)]
    lod = LoDArray.from_sequences(seqs, capacity=16, max_seqs=4)
    assert lod.capacity == 16
    assert int(lod.num_seqs) == 2
    np.testing.assert_array_equal(np.asarray(lod.lengths), [3, 5, 0, 0])
    batched, mask = lod.to_batch(max_len=8)
    assert batched.shape == (8, 4, 1)
    assert mask[:3, 0].all() and not mask[3, 0]
    back = LoDArray.from_batch(batched, mask, lod)
    np.testing.assert_allclose(np.asarray(back.data), np.asarray(lod.data))


def test_rng_determinism_under_grad():
    """Dropout must see identical masks in forward and re-traced grad."""
    x = pt.layers.data("x", shape=[8])
    h = pt.layers.fc(x, size=8, param_attr=pt.ParamAttr(name="w2"),
                     bias_attr=False)
    d = pt.layers.dropout(h, dropout_prob=0.5)
    loss = pt.layers.mean(d)
    pt.append_backward(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    xv = np.ones((4, 8), np.float32)
    g, l = exe.run(feed={"x": xv}, fetch_list=["w2@GRAD", loss])
    assert np.isfinite(g).all()
