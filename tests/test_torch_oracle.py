"""Cross-implementation oracles against torch CPU.

Reference test strategy §4.2 (SURVEY.md): every kernel family checked
against an independent implementation (there: CPU vs GPU / plain vs MKLDNN;
here: XLA vs torch CPU) — conv/conv_transpose (forward + weight grads,
bias/act paths), pool, batch_norm (train stats).
"""

import numpy as np
import pytest
import torch
import torch.nn.functional as F

import paddle_tpu as pt


def _run(feeds, fetch):
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return exe.run(feed=feeds, fetch_list=fetch)


@pytest.mark.parametrize(
    "stride,pad,dil,groups", [(1, 1, 1, 1), (2, 0, 1, 1), (1, 2, 2, 1), (1, 1, 1, 2)]
)
def test_conv2d_matches_torch(stride, pad, dil, groups):
    rng = np.random.RandomState(0)
    x = rng.randn(2, 4, 9, 9).astype(np.float32)
    w = rng.randn(6, 4 // groups, 3, 3).astype(np.float32)
    b = rng.randn(6).astype(np.float32)

    xv = pt.layers.data("x", shape=[4, 9, 9])
    out = pt.layers.conv2d(
        xv, num_filters=6, filter_size=3, stride=stride, padding=pad,
        dilation=dil, groups=groups,
        param_attr=pt.ParamAttr(name="cw"), bias_attr=pt.ParamAttr(name="cb"),
    )
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.global_scope().set("cw", w)
    pt.global_scope().set("cb", b)
    (got,) = exe.run(feed={"x": x}, fetch_list=[out])

    want = F.conv2d(
        torch.tensor(x), torch.tensor(w), torch.tensor(b),
        stride=stride, padding=pad, dilation=dil, groups=groups,
    ).numpy()
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_conv2d_transpose_matches_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 5, 5).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)  # [in_c, out_c, kh, kw]

    xv = pt.layers.data("x", shape=[4, 5, 5])
    out = pt.layers.conv2d_transpose(
        xv, num_filters=3, filter_size=3, stride=2, padding=1,
        param_attr=pt.ParamAttr(name="tw"),
    )
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.global_scope().set("tw", w)
    (got,) = exe.run(feed={"x": x}, fetch_list=[out])

    want = F.conv_transpose2d(
        torch.tensor(x), torch.tensor(w), stride=2, padding=1
    ).numpy()
    np.testing.assert_allclose(got, want, atol=1e-4)


def test_conv2d_transpose_bias_act_and_grads_match_torch():
    """Nonzero bias + relu forward, and input/weight gradients of the

    fractionally-strided formulation."""
    rng = np.random.RandomState(5)
    x = rng.randn(2, 4, 5, 5).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)
    b = rng.randn(3).astype(np.float32)

    xv = pt.layers.data("x", shape=[4, 5, 5])
    out = pt.layers.conv2d_transpose(
        xv, num_filters=3, filter_size=3, stride=2, padding=1,
        param_attr=pt.ParamAttr(name="tw2"),
        bias_attr=pt.ParamAttr(name="tb2"), act="relu",
    )
    loss = pt.layers.mean(pt.layers.elementwise_mul(out, out))
    pt.append_backward(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.global_scope().set("tw2", w)
    pt.global_scope().set("tb2", b)
    from paddle_tpu.core.program import grad_var_name

    got, gw = exe.run(
        feed={"x": x}, fetch_list=[out, grad_var_name("tw2")]
    )

    xt = torch.tensor(x)
    wt = torch.tensor(w, requires_grad=True)
    bt = torch.tensor(b, requires_grad=True)
    yt = torch.relu(
        F.conv_transpose2d(xt, wt, bt, stride=2, padding=1)
    )
    np.testing.assert_allclose(got, yt.detach().numpy(), atol=1e-4)
    (yt * yt).mean().backward()
    np.testing.assert_allclose(gw, wt.grad.numpy(), atol=1e-4)


@pytest.mark.parametrize("ptype", ["max", "avg"])
def test_pool2d_matches_torch(ptype):
    rng = np.random.RandomState(2)
    x = rng.randn(2, 3, 8, 8).astype(np.float32)
    xv = pt.layers.data("x", shape=[3, 8, 8])
    out = pt.layers.pool2d(xv, pool_size=3, pool_type=ptype, pool_stride=2,
                           pool_padding=1)
    (got,) = _run({"x": x}, [out])
    t = torch.tensor(x)
    if ptype == "max":
        want = F.max_pool2d(t, 3, stride=2, padding=1).numpy()
    else:
        want = F.avg_pool2d(t, 3, stride=2, padding=1,
                            count_include_pad=False).numpy()
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_batch_norm_matches_torch():
    rng = np.random.RandomState(3)
    x = rng.randn(4, 5, 6, 6).astype(np.float32)
    xv = pt.layers.data("x", shape=[5, 6, 6])
    out = pt.layers.batch_norm(xv, momentum=0.9)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    (got,) = exe.run(feed={"x": x}, fetch_list=[out])

    bn = torch.nn.BatchNorm2d(5, momentum=0.1, eps=1e-5)  # torch momentum = 1-ours
    bn.train()
    want = bn(torch.tensor(x)).detach().numpy()
    np.testing.assert_allclose(got, want, atol=1e-4)
    # running mean matches torch exactly (new = 0.9*old + 0.1*batch).
    # running VAR intentionally differs: the reference (and this kernel)
    # accumulate the BIASED batch variance while torch uses unbiased —
    # assert with the tolerance that difference implies (factor n/(n-1))
    prog = pt.default_main_program()
    bn_op = [op for b in prog.blocks for op in b.ops
             if op.type == "batch_norm"][0]
    got_mean = np.asarray(pt.global_scope().get(bn_op.inputs["Mean"][0]))
    np.testing.assert_allclose(
        got_mean, bn.running_mean.numpy(), atol=1e-4)
    got_var = np.asarray(pt.global_scope().get(bn_op.inputs["Variance"][0]))
    n = x.shape[0] * x.shape[2] * x.shape[3]
    biased_running = 0.9 * 1.0 + 0.1 * (
        bn.running_var.numpy() * 10 - 9.0  # invert torch's update
    ) * (n - 1) / n
    np.testing.assert_allclose(got_var, biased_running, atol=1e-4)


def test_conv2d_gradients_match_torch():
    """Input and weight gradients of conv via the framework autodiff."""
    rng = np.random.RandomState(4)
    x = rng.randn(2, 3, 7, 7).astype(np.float32)
    w = rng.randn(4, 3, 3, 3).astype(np.float32)

    xv = pt.layers.data("x", shape=[3, 7, 7])
    out = pt.layers.conv2d(xv, num_filters=4, filter_size=3,
                           param_attr=pt.ParamAttr(name="gw"),
                           bias_attr=False)
    loss = pt.layers.mean(pt.layers.elementwise_mul(out, out))
    pt.append_backward(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.global_scope().set("gw", w)
    from paddle_tpu.core.program import grad_var_name

    (gw,) = exe.run(feed={"x": x}, fetch_list=[grad_var_name("gw")])

    xt = torch.tensor(x)
    wt = torch.tensor(w, requires_grad=True)
    yt = F.conv2d(xt, wt)
    (yt * yt).mean().backward()
    np.testing.assert_allclose(gw, wt.grad.numpy(), atol=1e-4)
