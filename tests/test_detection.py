"""SSD detection family tests.

Reference analogues: gserver/tests/test_PriorBox.cpp,
test_DetectionOutput.cpp, and the MultiBoxLoss cases in
test_LayerGrad.cpp. Prior boxes checked against a direct reimplementation
of the PriorBox.cpp loop; detection_output checked to decode and NMS an
obvious box; multibox_loss checked to be trainable (loss decreases as
predictions approach encoded targets).
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops.detection_ops import (
    decode_boxes,
    encode_boxes,
    iou_matrix,
    make_prior_boxes,
)


def test_prior_box_matches_reference_loop():
    boxes, var = make_prior_boxes(
        layer_h=2, layer_w=2, image_h=32, image_w=32,
        min_sizes=[8.0], max_sizes=[16.0], aspect_ratios=[2.0],
        variance=[0.1, 0.1, 0.2, 0.2],
    )
    # ars = [1, 2, 0.5] → 3 + 1 max-size square = 4 priors per location
    assert boxes.shape == (2 * 2 * 4, 4)
    # first prior: center (8,8)/32=0.25, min_size 8 square → 8/32=0.25 wide
    np.testing.assert_allclose(
        boxes[0], [0.25 - 0.125, 0.25 - 0.125, 0.25 + 0.125, 0.25 + 0.125],
        rtol=1e-6,
    )
    # second prior: ar=2 → w=8*sqrt2, h=8/sqrt2
    w = 8 * np.sqrt(2) / 32 / 2
    h = 8 / np.sqrt(2) / 32 / 2
    np.testing.assert_allclose(
        boxes[1], [0.25 - w, 0.25 - h, 0.25 + w, 0.25 + h], rtol=1e-6
    )
    # max-size square prior is the last of the 4: sqrt(8*16)
    s = np.sqrt(8 * 16.0) / 32 / 2
    np.testing.assert_allclose(
        boxes[3], [0.25 - s, 0.25 - s, 0.25 + s, 0.25 + s], rtol=1e-6
    )
    assert (boxes >= 0).all() and (boxes <= 1).all()  # clipped
    np.testing.assert_allclose(var, np.tile([[0.1, 0.1, 0.2, 0.2]], (16, 1)))


def test_prior_box_layer():
    feat = pt.layers.data("feat", shape=[4, 3, 3])
    img = pt.layers.data("img", shape=[3, 24, 24])
    boxes, var = pt.layers.prior_box(
        feat, img, min_sizes=[6.0], aspect_ratios=[1.0],
        variances=[0.1, 0.1, 0.2, 0.2],
    )
    exe = pt.Executor()
    bv, vv = exe.run(
        feed={"feat": np.zeros((1, 4, 3, 3), np.float32),
              "img": np.zeros((1, 3, 24, 24), np.float32)},
        fetch_list=[boxes, var],
    )
    assert bv.shape == (9, 4) and vv.shape == (9, 4)


def test_encode_decode_roundtrip():
    import jax.numpy as jnp

    rng = np.random.RandomState(0)
    priors = np.array([[0.1, 0.1, 0.4, 0.5], [0.3, 0.3, 0.9, 0.8]], np.float32)
    var = np.tile([[0.1, 0.1, 0.2, 0.2]], (2, 1)).astype(np.float32)
    gt = np.array([[0.15, 0.12, 0.45, 0.52], [0.25, 0.35, 0.85, 0.75]],
                  np.float32)
    enc = encode_boxes(jnp.asarray(gt), jnp.asarray(priors), jnp.asarray(var))
    dec = decode_boxes(enc, jnp.asarray(priors), jnp.asarray(var))
    np.testing.assert_allclose(np.asarray(dec), gt, rtol=1e-5, atol=1e-6)


def test_iou_matrix():
    import jax.numpy as jnp

    a = jnp.asarray([[0.0, 0.0, 1.0, 1.0]])
    b = jnp.asarray([[0.0, 0.0, 1.0, 1.0], [0.5, 0.5, 1.5, 1.5],
                     [2.0, 2.0, 3.0, 3.0]])
    m = np.asarray(iou_matrix(a, b))
    np.testing.assert_allclose(m[0], [1.0, 0.25 / 1.75, 0.0], rtol=1e-5)


def test_detection_output_recovers_box():
    k = 4
    priors_np = np.array(
        [[0.0, 0.0, 0.2, 0.2], [0.4, 0.4, 0.6, 0.6], [0.7, 0.7, 0.9, 0.9],
         [0.1, 0.6, 0.3, 0.9]], np.float32)
    var_np = np.tile([[0.1, 0.1, 0.2, 0.2]], (k, 1)).astype(np.float32)

    loc = pt.layers.data("loc", shape=[k, 4])
    conf = pt.layers.data("conf", shape=[k, 3])
    priors = pt.layers.data("priors", shape=[4], append_batch_size=True)
    pvar = pt.layers.data("pvar", shape=[4], append_batch_size=True)
    det = pt.layers.detection_output(loc, conf, priors, pvar,
                                     confidence_threshold=0.3, keep_top_k=5)
    exe = pt.Executor()
    # zero loc offsets → decoded boxes == priors; prior 1 is class 1, hot
    locv = np.zeros((1, k, 4), np.float32)
    confv = np.full((1, k, 3), -5.0, np.float32)
    confv[0, 1, 1] = 5.0  # prior 1 strongly class 1
    confv[0, :, 0] = 2.0  # background elsewhere
    confv[0, 1, 0] = -5.0
    (out,) = exe.run(
        feed={"loc": locv, "conf": confv, "priors": priors_np, "pvar": var_np},
        fetch_list=[det],
    )
    assert out.shape == (1, 5, 6)
    top = out[0, 0]
    assert top[0] == 1.0  # class label
    assert top[1] > 0.9  # confidence
    np.testing.assert_allclose(top[2:], priors_np[1], atol=1e-5)
    # remaining slots empty
    assert (out[0, 1:, 0] == -1).all()


def test_detection_output_nms_suppresses_overlaps():
    k = 3
    priors_np = np.array(
        [[0.1, 0.1, 0.5, 0.5], [0.12, 0.12, 0.52, 0.52],
         [0.6, 0.6, 0.9, 0.9]], np.float32)
    var_np = np.tile([[0.1, 0.1, 0.2, 0.2]], (k, 1)).astype(np.float32)
    loc = pt.layers.data("loc", shape=[k, 4])
    conf = pt.layers.data("conf", shape=[k, 2])
    priors = pt.layers.data("priors", shape=[4], append_batch_size=True)
    pvar = pt.layers.data("pvar", shape=[4], append_batch_size=True)
    det = pt.layers.detection_output(loc, conf, priors, pvar,
                                     confidence_threshold=0.3,
                                     nms_threshold=0.5, keep_top_k=3)
    exe = pt.Executor()
    locv = np.zeros((1, k, 4), np.float32)
    confv = np.zeros((1, k, 2), np.float32)
    confv[0, :, 1] = [4.0, 3.9, 3.8]  # all strongly class 1
    confv[0, :, 0] = -4.0
    (out,) = exe.run(
        feed={"loc": locv, "conf": confv, "priors": priors_np, "pvar": var_np},
        fetch_list=[det],
    )
    labels = out[0, :, 0]
    # priors 0 and 1 overlap heavily → one suppressed; prior 2 kept
    assert (labels == 1.0).sum() == 2


def test_multibox_loss_trains():
    rng = np.random.RandomState(1)
    k = 8
    priors_np, var_np = make_prior_boxes(2, 2, 16, 16, [6.0], [], [2.0],
                                         [0.1, 0.1, 0.2, 0.2])
    k = priors_np.shape[0]
    gt_np = np.array([[[0.05, 0.05, 0.45, 0.45], [0.5, 0.5, 0.95, 0.95]]],
                     np.float32)
    gtl_np = np.array([[1, 2]], np.int32)

    loc = pt.layers.data("loc", shape=[k, 4])
    feat = pt.layers.data("feat", shape=[k * 6])
    priors = pt.layers.data("priors", shape=[4], append_batch_size=True)
    pvar = pt.layers.data("pvar", shape=[4], append_batch_size=True)
    gt = pt.layers.data("gt", shape=[2, 4])
    gtl = pt.layers.data("gtl", shape=[2], dtype=np.int32)
    locp = pt.layers.fc(feat, size=k * 4)
    confp = pt.layers.fc(feat, size=k * 3)
    loss = pt.layers.mean(pt.layers.multibox_loss(
        locp, confp, priors, pvar, gt, gtl, overlap_threshold=0.3))
    pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    featv = rng.randn(1, k * 6).astype(np.float32)
    losses = []
    for _ in range(40):
        (l,) = exe.run(
            feed={"feat": featv, "priors": priors_np, "pvar": var_np,
                  "gt": gt_np, "gtl": gtl_np, "loc": np.zeros((1, k, 4), np.float32)},
            fetch_list=[loss],
        )
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    assert np.isfinite(losses).all()
