"""Fused Bahdanau attention decoder parity (ops/bahdanau_kernels.py).

Reference: the hand-written fused recurrent kernels the reference used
for its hot cells (cuda/include/hl_lstm.h:42); the decoder semantics
under test are the book simple_attention GRU decoder
(trainer_config_helpers/networks.py) as implemented by the XLA scan in
ops/attention_ops.py. The fused path (Pallas kernels in interpret mode
on CPU + the whole-scan custom VJP) must reproduce the scan's forward
and every gradient.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.flags import FLAGS
from paddle_tpu.ops.attention_ops import _attention
from paddle_tpu.ops.bahdanau_kernels import (fused_attention_decoder,
                                             fused_decoder_eligible)
from paddle_tpu.ops.rnn_ops import gru_cell


def _scan_decoder(enc_b, enc_proj, enc_mask, trg_b, trg_mask, h0,
                  wa_dec, v_att, wx, wh, bias):
    """The reference XLA formulation (attention_ops.py step fn)."""

    def step(h_prev, inp):
        x_t, m_t = inp
        ctxv = _attention(h_prev, enc_b, enc_proj, enc_mask, wa_dec, v_att)
        xin = jnp.concatenate([x_t, ctxv], axis=-1)
        xp = jnp.dot(xin, wx,
                     preferred_element_type=jnp.float32).astype(x_t.dtype)
        xp = xp + bias
        h = gru_cell(xp, h_prev, wh, jax.nn.sigmoid, jnp.tanh)
        m = m_t[:, None].astype(h.dtype)
        h = m * h + (1 - m) * h_prev
        return h, h

    _, h_seq = jax.lax.scan(step, h0, (trg_b, trg_mask))
    return h_seq


def _make_inputs(B=8, S=10, T=6, E=128, C=128, A=128, H=128, seed=3):
    rng = np.random.RandomState(seed)
    f32 = jnp.float32
    enc_b = jnp.asarray(rng.randn(B, S, C) * 0.3, f32)
    wa_enc = jnp.asarray(rng.randn(C, A) / np.sqrt(C), f32)
    enc_proj = jnp.dot(enc_b, wa_enc)
    lens = rng.randint(S // 2, S + 1, (B,))
    enc_mask = jnp.asarray(np.arange(S)[None, :] < lens[:, None])
    trg_b = jnp.asarray(rng.randn(T, B, E) * 0.3, f32)
    tlens = rng.randint(T // 2, T + 1, (B,))
    trg_mask = jnp.asarray(
        (np.arange(T)[:, None] < tlens[None, :]).astype(np.float32))
    h0 = jnp.asarray(rng.randn(B, H) * 0.1, f32)
    wa_dec = jnp.asarray(rng.randn(H, A) / np.sqrt(H), f32)
    v_att = jnp.asarray(rng.randn(A) / np.sqrt(A), f32)
    wx = jnp.asarray(rng.randn(E + C, 3 * H) / np.sqrt(E + C), f32)
    wh = jnp.asarray(rng.randn(H, 3 * H) / np.sqrt(H), f32)
    bias = jnp.asarray(rng.randn(3 * H) * 0.05, f32)
    return (enc_b, enc_proj, enc_mask, trg_b, trg_mask, h0, wa_dec, v_att,
            wx, wh, bias)


@pytest.fixture
def interpret_flag():
    FLAGS.fused_attention_interpret = True
    yield
    FLAGS.fused_attention_interpret = False


def test_eligibility_gates():
    assert not fused_decoder_eligible(8, 10, 100, 128, jnp.bfloat16)  # A%128
    assert not fused_decoder_eligible(9, 10, 128, 128, jnp.bfloat16)  # B%8
    if jax.default_backend() != "tpu":
        assert not fused_decoder_eligible(8, 10, 128, 128, jnp.bfloat16)
        FLAGS.fused_attention_interpret = True
        try:
            assert fused_decoder_eligible(8, 10, 128, 128, jnp.bfloat16)
        finally:
            FLAGS.fused_attention_interpret = False


@pytest.mark.parametrize("seq_fwd", [True, False])
def test_fused_decoder_forward_parity(interpret_flag, seq_fwd):
    """Both forward formulations — the per-step kernel inside lax.scan
    (default) and the whole-sequence kernel — match the XLA scan."""
    prev = FLAGS.fused_attention_seq_fwd
    FLAGS.fused_attention_seq_fwd = seq_fwd
    try:
        args = _make_inputs()
        ref = _scan_decoder(*args)
        got = fused_attention_decoder(*args)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        FLAGS.fused_attention_seq_fwd = prev


def test_fused_decoder_gradient_parity(interpret_flag):
    args = _make_inputs()
    # differentiate wrt everything float except the masks (idx 2, 4)
    argnums = (0, 1, 3, 5, 6, 7, 8, 9, 10)
    names = ["enc_b", "enc_proj", "trg_b", "h0", "wa_dec", "v_att",
             "wx", "wh", "bias"]

    def loss(fn):
        def f(*diff_args):
            full = list(args)
            for i, a in zip(argnums, diff_args):
                full[i] = a
            h = fn(*full)
            # nonuniform readout so every position/feature matters
            w = jnp.arange(h.size, dtype=h.dtype).reshape(h.shape) * 1e-4
            return jnp.sum(h * jnp.sin(w))
        return f

    diff_args = tuple(args[i] for i in argnums)
    g_ref = jax.grad(loss(_scan_decoder), argnums=tuple(range(len(argnums))))(
        *diff_args)
    g_got = jax.grad(loss(fused_attention_decoder),
                     argnums=tuple(range(len(argnums))))(*diff_args)
    for name, a, b in zip(names, g_got, g_ref):
        scale = max(1e-3, float(np.abs(np.asarray(b)).max()))
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4 * scale,
            err_msg=f"grad {name}")


def test_fused_decoder_in_model(interpret_flag):
    """The seq2seq model dispatches through the fused path when eligible
    and trains: loss drops over a few Adam steps (CPU interpret mode)."""
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.core.lod import LoDArray

    pt.reset()
    B, S, vocab = 8, 12, 120
    src = pt.layers.data("src", shape=[-1], dtype=np.int32, lod_level=1,
                         append_batch_size=False)
    trg_in = pt.layers.data("trg_in", shape=[-1], dtype=np.int32,
                            lod_level=1, append_batch_size=False)
    label = pt.layers.data("label", shape=[-1], dtype=np.int32,
                           lod_level=1, append_batch_size=False)
    logits = models.seq2seq_attention(
        src, trg_in, src_vocab=vocab, trg_vocab=vocab, emb_dim=128,
        enc_hidden=128, dec_hidden=128, src_max_len=S, trg_max_len=S)
    tok_loss = pt.layers.softmax_with_cross_entropy(logits, label)
    loss = pt.layers.mean(pt.layers.sequence_pool(tok_loss, "sum"))
    pt.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    exe = pt.Executor()
    pt.default_startup_program().random_seed = 5
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    pack = lambda seqs: LoDArray.from_sequences(  # noqa: E731
        seqs, capacity=B * S, max_seqs=B)
    seqs = [rng.randint(2, vocab, (rng.randint(S // 2, S),)).astype(np.int32)
            for _ in range(B)]
    feed = {"src": pack(seqs), "trg_in": pack(seqs), "label": pack(seqs)}
    losses = []
    for _ in range(8):
        (l,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
