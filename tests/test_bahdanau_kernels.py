"""Fused Bahdanau attention decoder parity (ops/bahdanau_kernels.py).

Reference: the hand-written fused recurrent kernels the reference used
for its hot cells (cuda/include/hl_lstm.h:42); the decoder semantics
under test are the book simple_attention GRU decoder
(trainer_config_helpers/networks.py) as implemented by the XLA scan in
ops/attention_ops.py. The fused path (Pallas kernels in interpret mode
on CPU + the whole-scan custom VJP) must reproduce the scan's forward
and every gradient.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.flags import FLAGS
from paddle_tpu.ops.attention_ops import _attention
from paddle_tpu.ops.bahdanau_kernels import (fused_attention_decoder,
                                             fused_decoder_eligible)
from paddle_tpu.ops.rnn_ops import gru_cell


def _scan_decoder(enc_b, enc_proj, enc_mask, trg_b, trg_mask, h0,
                  wa_dec, v_att, wx, wh, bias):
    """The reference XLA formulation (attention_ops.py step fn)."""

    def step(h_prev, inp):
        x_t, m_t = inp
        ctxv = _attention(h_prev, enc_b, enc_proj, enc_mask, wa_dec, v_att)
        xin = jnp.concatenate([x_t, ctxv], axis=-1)
        xp = jnp.dot(xin, wx,
                     preferred_element_type=jnp.float32).astype(x_t.dtype)
        xp = xp + bias
        h = gru_cell(xp, h_prev, wh, jax.nn.sigmoid, jnp.tanh)
        m = m_t[:, None].astype(h.dtype)
        h = m * h + (1 - m) * h_prev
        return h, h

    _, h_seq = jax.lax.scan(step, h0, (trg_b, trg_mask))
    return h_seq


def _make_inputs(B=8, S=10, T=6, E=128, C=128, A=128, H=128, seed=3):
    rng = np.random.RandomState(seed)
    f32 = jnp.float32
    enc_b = jnp.asarray(rng.randn(B, S, C) * 0.3, f32)
    wa_enc = jnp.asarray(rng.randn(C, A) / np.sqrt(C), f32)
    enc_proj = jnp.dot(enc_b, wa_enc)
    lens = rng.randint(S // 2, S + 1, (B,))
    enc_mask = jnp.asarray(np.arange(S)[None, :] < lens[:, None])
    trg_b = jnp.asarray(rng.randn(T, B, E) * 0.3, f32)
    tlens = rng.randint(T // 2, T + 1, (B,))
    trg_mask = jnp.asarray(
        (np.arange(T)[:, None] < tlens[None, :]).astype(np.float32))
    h0 = jnp.asarray(rng.randn(B, H) * 0.1, f32)
    wa_dec = jnp.asarray(rng.randn(H, A) / np.sqrt(H), f32)
    v_att = jnp.asarray(rng.randn(A) / np.sqrt(A), f32)
    wx = jnp.asarray(rng.randn(E + C, 3 * H) / np.sqrt(E + C), f32)
    wh = jnp.asarray(rng.randn(H, 3 * H) / np.sqrt(H), f32)
    bias = jnp.asarray(rng.randn(3 * H) * 0.05, f32)
    return (enc_b, enc_proj, enc_mask, trg_b, trg_mask, h0, wa_dec, v_att,
            wx, wh, bias)


@pytest.fixture
def interpret_flag():
    FLAGS.fused_attention_interpret = True
    yield
    FLAGS.fused_attention_interpret = False


def test_eligibility_gates():
    assert not fused_decoder_eligible(8, 10, 100, 128, jnp.bfloat16)  # A%128
    assert not fused_decoder_eligible(9, 10, 128, 128, jnp.bfloat16)  # B%8
    if jax.default_backend() != "tpu":
        assert not fused_decoder_eligible(8, 10, 128, 128, jnp.bfloat16)
        FLAGS.fused_attention_interpret = True
        try:
            assert fused_decoder_eligible(8, 10, 128, 128, jnp.bfloat16)
        finally:
            FLAGS.fused_attention_interpret = False


@pytest.mark.parametrize("seq_fwd", [True, False])
def test_fused_decoder_forward_parity(interpret_flag, seq_fwd):
    """Both forward formulations — the per-step kernel inside lax.scan
    (default) and the whole-sequence kernel — match the XLA scan."""
    prev = FLAGS.fused_attention_seq_fwd
    FLAGS.fused_attention_seq_fwd = seq_fwd
    try:
        args = _make_inputs()
        ref = _scan_decoder(*args)
        got = fused_attention_decoder(*args)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
    finally:
        FLAGS.fused_attention_seq_fwd = prev


@pytest.mark.parametrize("seq_bwd", [True, False])
def test_fused_decoder_gradient_parity(interpret_flag, seq_bwd):
    """Both backward formulations — the reverse scan of per-step kernels
    (default) and the whole-sequence mega kernel — reproduce every
    gradient of the XLA scan. (The mega kernel ships off by default —
    measured 0.963x, benchmarks/bahdanau_megabwd.json — but stays
    parity-tested: vs f64 ground truth it is the MORE accurate path.)"""
    from paddle_tpu.ops import bahdanau_kernels as bk

    prev = FLAGS.fused_attention_seq_bwd
    FLAGS.fused_attention_seq_bwd = seq_bwd
    bk.reset_dispatch_stats()
    try:
        args = _make_inputs()
        # differentiate wrt everything float except the masks (idx 2, 4)
        argnums = (0, 1, 3, 5, 6, 7, 8, 9, 10)
        names = ["enc_b", "enc_proj", "trg_b", "h0", "wa_dec", "v_att",
                 "wx", "wh", "bias"]

        def loss(fn):
            def f(*diff_args):
                full = list(args)
                for i, a in zip(argnums, diff_args):
                    full[i] = a
                h = fn(*full)
                # nonuniform readout so every position/feature matters
                w = jnp.arange(h.size, dtype=h.dtype).reshape(h.shape) * 1e-4
                return jnp.sum(h * jnp.sin(w))
            return f

        diff_args = tuple(args[i] for i in argnums)
        g_ref = jax.grad(loss(_scan_decoder),
                         argnums=tuple(range(len(argnums))))(*diff_args)
        g_got = jax.grad(loss(fused_attention_decoder),
                         argnums=tuple(range(len(argnums))))(*diff_args)
        for name, a, b in zip(names, g_got, g_ref):
            scale = max(1e-3, float(np.abs(np.asarray(b)).max()))
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-4, atol=5e-4 * scale,
                err_msg=f"grad {name}")
        want = "seq_bwd" if seq_bwd else "scan_bwd"
        assert bk.dispatch_stats[want] >= 1, bk.dispatch_stats
    finally:
        FLAGS.fused_attention_seq_bwd = prev


@pytest.mark.parametrize("seq_bwd", [True, False])
def test_fused_decoder_bf16_parity(interpret_flag, seq_bwd):
    """bf16 io (what the decoder actually runs under AMP since the
    round-5 cast fix) compiles and tracks the bf16 XLA scan — through
    BOTH backwards. Gradients compare at bf16-appropriate tolerance
    (the kernels accumulate f32 in VMEM, the scan accumulates through a
    bf16 carry — the kernels are the more accurate side, so the
    comparison bounds kernel error)."""
    from paddle_tpu.ops import bahdanau_kernels as bk

    prev = FLAGS.fused_attention_seq_bwd
    FLAGS.fused_attention_seq_bwd = seq_bwd
    bk.reset_dispatch_stats()
    try:
        args = tuple(
            a.astype(jnp.bfloat16)
            if hasattr(a, "dtype") and a.dtype == jnp.float32 else a
            for a in _make_inputs())
        ref = _scan_decoder(*args)
        got = fused_attention_decoder(*args)
        np.testing.assert_allclose(np.asarray(got, np.float32),
                                   np.asarray(ref, np.float32),
                                   rtol=3e-2, atol=3e-2)

        def loss(fn):
            def f(enc_b, wx):
                full = list(args)
                full[0], full[8] = enc_b, wx
                return jnp.sum(fn(*full).astype(jnp.float32) ** 2)
            return f

        g_ref = jax.grad(loss(_scan_decoder), argnums=(0, 1))(
            args[0], args[8])
        g_got = jax.grad(loss(fused_attention_decoder), argnums=(0, 1))(
            args[0], args[8])
        for a, b in zip(g_got, g_ref):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            scale = max(1.0, np.abs(b).max())
            np.testing.assert_allclose(a, b, rtol=6e-2, atol=6e-2 * scale)
        want = "seq_bwd" if seq_bwd else "scan_bwd"
        assert bk.dispatch_stats[want] >= 1, bk.dispatch_stats
    finally:
        FLAGS.fused_attention_seq_bwd = prev


def test_bench_geometry_engages_fused_path(interpret_flag):
    """The bench-default NMT geometries must be ELIGIBLE — a config
    drifting off the eligibility grid (A/C alignment, batch-tile
    divisibility) would silently fall back to the scan and the headline
    would quietly regress (VERDICT r4 weak #3)."""
    # bs256 bench default and bs128: S=T=50, A=512, bidirectional C=1024,
    # bf16 under AMP (the production io dtype since round 5) and f32
    for dtype in (jnp.bfloat16, jnp.float32):
        assert fused_decoder_eligible(256, 50, 512, 1024, dtype)
        assert fused_decoder_eligible(128, 50, 512, 1024, dtype)
    # small batches stay eligible through the 8->4->2 tile ladder (legal
    # only when the tile spans the batch dim); a batch a sub-8 tile
    # would only DIVIDE (250 = 2 x 125) must fall back to the scan —
    # that block shape fails Mosaic's (8k, 128k)-or-full tiling rule
    assert fused_decoder_eligible(4, 50, 512, 1024, jnp.bfloat16)
    assert fused_decoder_eligible(2, 50, 512, 1024, jnp.bfloat16)
    assert not fused_decoder_eligible(250, 50, 512, 1024, jnp.bfloat16)
    # the mega-bwd VMEM model passes at the bench geometry in bf16 (it
    # is an opt-in path, but an ineligible default geometry would make
    # the flag a no-op silently)
    from paddle_tpu.ops.bahdanau_kernels import (_mega_bwd_vmem_ok,
                                                 _pad_s)
    assert _mega_bwd_vmem_ok(256, _pad_s(50), 512, 1024, 512, 50, 2)
    # and the fused path actually DISPATCHES at the bench geometry, not
    # just passes the predicate: trace the decoder fwd+bwd at the real
    # shapes (jax.eval_shape — abstract, no FLOPs) and assert the
    # trace-time counters fired. A trace-time condition diverging from
    # the eligibility predicate would slip past the asserts above.
    from paddle_tpu.ops import bahdanau_kernels as bk

    B, S, T, E, C, A, H = 256, 50, 50, 512, 1024, 512, 512
    dt = jnp.bfloat16
    shapes = (
        jax.ShapeDtypeStruct((B, S, C), dt),            # enc_b
        jax.ShapeDtypeStruct((B, S, A), dt),            # enc_proj
        jax.ShapeDtypeStruct((B, S), jnp.bool_),        # enc_mask
        jax.ShapeDtypeStruct((T, B, E), dt),            # trg_b
        jax.ShapeDtypeStruct((T, B), jnp.float32),      # trg_mask
        jax.ShapeDtypeStruct((B, H), dt),               # h0
        jax.ShapeDtypeStruct((H, A), dt),               # wa_dec
        jax.ShapeDtypeStruct((A,), dt),                 # v_att
        jax.ShapeDtypeStruct((E + C, 3 * H), dt),       # wx
        jax.ShapeDtypeStruct((H, 3 * H), dt),           # wh
        jax.ShapeDtypeStruct((3 * H,), dt),             # bias
    )
    bk.reset_dispatch_stats()

    def loss(enc_b, ep, *rest):
        return jnp.sum(
            fused_attention_decoder(enc_b, ep, *rest).astype(jnp.float32))

    jax.eval_shape(jax.grad(loss, argnums=(0, 1)), *shapes)
    assert bk.dispatch_stats["fused_calls"] >= 1, bk.dispatch_stats
    assert bk.dispatch_stats["scan_bwd"] >= 1, bk.dispatch_stats


def test_decoder_applies_amp_cast(interpret_flag):
    """Under Program.set_amp the decoder op must cast its io to the amp
    dtype: trg_emb arrives f32 straight from the embedding gather and
    would otherwise pin the whole decoder — and the fused kernels'
    [B, S, A] streams — to f32 (round-5 fix; moved the NMT headline
    262k -> 324k tok/s)."""
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.core.lod import LoDArray
    from paddle_tpu.ops import bahdanau_kernels as bk

    seen = []
    orig = bk.fused_decoder_eligible

    def spy(B, S, A, C, dtype):
        seen.append(jnp.dtype(dtype))
        return orig(B, S, A, C, dtype)

    bk.fused_decoder_eligible = spy
    try:
        pt.reset()
        B, S, vocab = 8, 12, 64
        src = pt.layers.data("src", shape=[-1], dtype=np.int32, lod_level=1,
                             append_batch_size=False)
        trg_in = pt.layers.data("trg_in", shape=[-1], dtype=np.int32,
                                lod_level=1, append_batch_size=False)
        logits = models.seq2seq_attention(
            src, trg_in, src_vocab=vocab, trg_vocab=vocab, emb_dim=128,
            enc_hidden=128, dec_hidden=128, src_max_len=S, trg_max_len=S)
        prog = pt.default_main_program()
        prog.set_amp("bfloat16")
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        rng = np.random.RandomState(0)
        pack = lambda seqs: LoDArray.from_sequences(  # noqa: E731
            seqs, capacity=B * S, max_seqs=B)
        seqs = [rng.randint(2, vocab, (S,)).astype(np.int32)
                for _ in range(B)]
        exe.run(feed={"src": pack(seqs), "trg_in": pack(seqs)},
                fetch_list=[logits])
        assert seen and all(d == jnp.bfloat16 for d in seen), seen
    finally:
        bk.fused_decoder_eligible = orig


def test_fused_decoder_in_model(interpret_flag):
    """The seq2seq model dispatches through the fused path when eligible
    and trains: loss drops over a few Adam steps (CPU interpret mode)."""
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.core.lod import LoDArray

    pt.reset()
    B, S, vocab = 8, 12, 120
    src = pt.layers.data("src", shape=[-1], dtype=np.int32, lod_level=1,
                         append_batch_size=False)
    trg_in = pt.layers.data("trg_in", shape=[-1], dtype=np.int32,
                            lod_level=1, append_batch_size=False)
    label = pt.layers.data("label", shape=[-1], dtype=np.int32,
                           lod_level=1, append_batch_size=False)
    logits = models.seq2seq_attention(
        src, trg_in, src_vocab=vocab, trg_vocab=vocab, emb_dim=128,
        enc_hidden=128, dec_hidden=128, src_max_len=S, trg_max_len=S)
    tok_loss = pt.layers.softmax_with_cross_entropy(logits, label)
    loss = pt.layers.mean(pt.layers.sequence_pool(tok_loss, "sum"))
    pt.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    exe = pt.Executor()
    pt.default_startup_program().random_seed = 5
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    pack = lambda seqs: LoDArray.from_sequences(  # noqa: E731
        seqs, capacity=B * S, max_seqs=B)
    seqs = [rng.randint(2, vocab, (rng.randint(S // 2, S),)).astype(np.int32)
            for _ in range(B)]
    feed = {"src": pack(seqs), "trg_in": pack(seqs), "label": pack(seqs)}
    losses = []
    for _ in range(8):
        (l,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(l))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses
