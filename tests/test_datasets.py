"""Dataset zoo schema tests (reference: python/paddle/v2/tests/test_*.py
dataset tests check sample counts and schemas)."""

import numpy as np

from paddle_tpu.data.datasets import (
    cifar,
    conll05,
    flowers,
    imikolov,
    mnist,
    movielens,
    mq2007,
    sentiment,
    uci_housing,
    voc2012,
    wmt14,
    wmt16,
)


def _first(reader):
    return next(iter(reader()))


def test_cifar_schema():
    img, lbl = _first(cifar.train10())
    assert img.shape == (3072,) and img.dtype == np.float32
    assert 0 <= lbl < 10
    img, lbl = _first(cifar.train100())
    assert 0 <= lbl < 100
    assert 0.0 <= img.min() and img.max() <= 1.0


def test_imikolov_ngram_and_seq():
    d = imikolov.build_dict()
    assert "<s>" in d and "<e>" in d and "<unk>" in d
    grams = list(imikolov.train(d, 5)())
    assert all(len(g) == 5 for g in grams[:50])
    v = len(d)
    assert all(0 <= w < v for g in grams[:50] for w in g)
    seq = _first(imikolov.train(d, 5, imikolov.DataType.SEQ))
    assert seq[0] == d["<s>"] and seq[-1] == d["<e>"]


def test_movielens_schema():
    s = _first(movielens.train())
    uid, gender, age, job, mid, cats, titles, score = s
    assert 1 <= uid <= movielens.max_user_id()
    assert gender in (0, 1)
    assert 0 <= age < len(movielens.age_table)
    assert 0 <= job <= movielens.max_job_id()
    assert 1 <= mid <= movielens.max_movie_id()
    assert isinstance(cats, list) and isinstance(titles, list)
    assert 1.0 <= score <= 5.0
    # ratings are learnable: same (uid,mid) re-sampled later stays consistent
    assert movielens.user_info()[uid]["gender"] == gender


def test_conll05_schema():
    w, v, l = conll05.get_dict()
    s = _first(conll05.train())
    assert len(s) == 9
    length = len(s[0])
    assert all(len(slot) == length for slot in s)
    assert sum(s[7]) == 1  # exactly one predicate mark
    assert all(0 <= t <= l["O"] for t in s[8])
    # ctx windows constant per sentence
    assert len(set(s[1])) == 1 and len(set(s[5])) == 1
    assert conll05.get_embedding().shape[1] == 32


def test_wmt14_translation_learnable():
    r = wmt14.train(dict_size=100)
    src, trg_in, trg_next = _first(r)
    assert trg_in[0] == wmt14.START_ID
    assert trg_next[-1] == wmt14.END_ID
    assert trg_in[1:] == trg_next[:-1]
    assert len(src) == len(trg_next) - 1
    # deterministic mapping: same src prefix ↔ same trg suffix rule
    s2, t2, _ = _first(wmt14.train(dict_size=100))
    assert (s2, t2) == (src, trg_in)
    sd, td = wmt14.get_dict(100)
    assert len(sd) == 100


def test_wmt16_schema():
    src, trg_in, trg_next = _first(wmt16.train(100, 100))
    assert trg_in[0] == wmt14.START_ID and trg_next[-1] == wmt14.END_ID


def test_sentiment_schema():
    ids, lbl = _first(sentiment.train())
    assert lbl in (0, 1) and all(isinstance(i, int) for i in ids)
    assert len(sentiment.get_word_dict()) == 2000


def test_mq2007_formats():
    f, r = _first(mq2007.train("pointwise"))
    assert f.shape == (mq2007.FEATURE_DIM,) and r in (0.0, 1.0, 2.0)
    hi, lo = _first(mq2007.train("pairwise"))
    assert hi.shape == lo.shape == (mq2007.FEATURE_DIM,)
    rels, feats = _first(mq2007.train("listwise"))
    assert len(rels) == feats.shape[0]


def test_flowers_voc_schema():
    img, lbl = _first(flowers.train())
    assert img.shape == (3, 64, 64) and 0 <= lbl < 102
    img, seg = _first(voc2012.train())
    assert img.shape == (3, 64, 64) and seg.shape == (64, 64)
    assert seg.max() < voc2012.N_CLASSES


def test_reader_determinism():
    a = [tuple(np.asarray(x).tolist() for x in s) for s in list(wmt14.test(50)())[:5]]
    b = [tuple(np.asarray(x).tolist() for x in s) for s in list(wmt14.test(50)())[:5]]
    assert a == b
