"""CTC loss vs torch.nn.CTCLoss (independent oracle — the reference's

cross-implementation test pattern, SURVEY §4.2: the same quantity computed
by two unrelated implementations must agree)."""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.lod import LoDArray
from paddle_tpu.ops.ctc_ops import ctc_loss

torch = pytest.importorskip("torch")


def test_ctc_matches_torch():
    rng = np.random.RandomState(0)
    C, blank = 6, 0
    in_lens = [7, 5, 9]
    lab_lens = [3, 2, 4]
    logits = [rng.randn(t, C).astype(np.float32) for t in in_lens]
    labels = [rng.randint(1, C, (l,)).astype(np.int32) for l in lab_lens]

    logits_l = LoDArray.from_sequences(logits, capacity=32, max_seqs=3)
    labels_l = LoDArray.from_sequences(labels, capacity=16, max_seqs=3)
    ours = np.asarray(ctc_loss(logits_l, labels_l, blank=blank))

    T = max(in_lens)
    padded = np.zeros((T, 3, C), np.float32)
    for i, lg in enumerate(logits):
        padded[: lg.shape[0], i] = lg
    log_probs = torch.log_softmax(torch.tensor(padded), dim=-1)
    flat_labels = torch.tensor(np.concatenate(labels).astype(np.int64))
    ref = torch.nn.CTCLoss(blank=blank, reduction="none")(
        log_probs,
        flat_labels,
        torch.tensor(in_lens),
        torch.tensor(lab_lens),
    ).numpy()
    np.testing.assert_allclose(ours[:3], ref, rtol=1e-4, atol=1e-4)


def test_ctc_layer_converges():
    """Tiny 'speech' task: frames are one-hot-ish encodings of a label

    sequence stretched 2x; CTC must learn the alignment."""
    rng = np.random.RandomState(1)
    C = 5  # classes incl. blank 0

    def make(n=8):
        xs, ys = [], []
        for _ in range(n):
            L = rng.randint(2, 4)
            y = rng.randint(1, C, (L,)).astype(np.int32)
            # each label emits 2 noisy frames
            frames = np.repeat(np.eye(C, dtype=np.float32)[y], 2, axis=0)
            frames += 0.1 * rng.randn(*frames.shape).astype(np.float32)
            xs.append(frames)
            ys.append(y)
        return (LoDArray.from_sequences(xs, capacity=64, max_seqs=n),
                LoDArray.from_sequences(ys, capacity=32, max_seqs=n))

    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 4
    with pt.program_guard(prog, startup):
        x = pt.layers.data("x", [-1, C], np.float32, lod_level=1,
                           append_batch_size=False)
        y = pt.layers.data("y", [-1], np.int32, lod_level=1,
                           append_batch_size=False)
        h = pt.layers.fc(x, size=32, act="relu")
        logits = pt.layers.fc(h, size=C)
        loss = pt.layers.warpctc(logits, y, blank=0, max_len=8,
                                 max_label_len=4)
        cost = pt.layers.mean(loss)
        pt.optimizer.Adam(learning_rate=0.02).minimize(cost)
    exe = pt.Executor()
    exe.run(startup)
    first = None
    for _ in range(120):
        xv, yv = make()
        (c,) = exe.run(prog, feed={"x": xv, "y": yv}, fetch_list=[cost])
        if first is None:
            first = float(c)
    assert float(c) < 0.3 * first, f"CTC did not converge: {first} -> {float(c)}"


def test_ctc_greedy_decoder_and_edit_distance():
    """Decode pipeline: greedy best-path + EditDistance evaluator

    (reference: CTCErrorEvaluator.cpp computes exactly this)."""
    from paddle_tpu.evaluator import EditDistance

    C = 5
    # frames spelling [2, 2, 3]: must collapse repeats only across
    # distinct emissions: 2,2,blank,2,3,3 → 2,2,3
    frames = np.array(
        [[0, 0, 9, 0, 0],  # 2
         [0, 0, 9, 0, 0],  # 2 (repeat, collapsed)
         [9, 0, 0, 0, 0],  # blank
         [0, 0, 9, 0, 0],  # 2 (new after blank)
         [0, 0, 0, 9, 0],  # 3
         [0, 0, 0, 9, 0]],  # 3 (repeat, collapsed)
        np.float32,
    )
    x = LoDArray.from_sequences([frames], capacity=16, max_seqs=1)
    prog = pt.Program()
    with pt.program_guard(prog, pt.Program()):
        xv = pt.layers.data("x", [-1, C], np.float32, lod_level=1,
                            append_batch_size=False)
        ids_v, lens_v = pt.layers.ctc_greedy_decoder(xv, blank=0, max_len=8)
    exe = pt.Executor()
    ids, lens = exe.run(prog, feed={"x": x}, fetch_list=[ids_v, lens_v])
    assert lens[0] == 3
    np.testing.assert_array_equal(ids[0, :3], [2, 2, 3])

    ed = EditDistance()
    ed.update([ids[0, : lens[0]]], [[2, 2, 3]])
    assert ed.eval() == 0.0
