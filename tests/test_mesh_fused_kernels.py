"""Fused Pallas kernels under a device mesh (VERDICT r4 weak #2).

The written policy (ops/mesh_dispatch.py): a Mosaic pallas_call cannot
be auto-partitioned by GSPMD, so under a ParallelExecutor mesh every
fused-kernel dispatch shard_maps itself over the dp axis — per-shard
kernels at the local batch, replicated weights, psum'd weight
cotangents. These tests prove, on the 8-virtual-device CPU mesh at
IN-WINDOW shapes (fused-LSTM H>=384; the Bahdanau decoder family):

- training under dp (and dp x mp) meshes with the fused kernels ON
  matches single-device training with the XLA scan formulations —
  losses AND updated weights (i.e. the psum'd dW/dWx/dv/... are right);
- the fused path actually DISPATCHED under the mesh (spy assertions —
  a silent fallback to the scan fails the test, not just runs slow);
- the bench-default NMT geometry dispatches fused under a dp4 mesh at
  the per-shard batch (trace-only, jax.eval_shape).

Reference analogue: test_CompareTwoNets.cpp (single-vs-multi numeric
equivalence) + the MultiGradientMachine replica contract.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import models, parallel as pp
from paddle_tpu.core.lod import LoDArray
from paddle_tpu.flags import FLAGS
from paddle_tpu.ops import bahdanau_kernels as bk
from paddle_tpu.ops import mesh_dispatch, pallas_kernels


@pytest.fixture
def fused_interpret():
    FLAGS.fused_rnn_interpret = True
    FLAGS.fused_attention_interpret = True
    yield
    FLAGS.fused_rnn_interpret = False
    FLAGS.fused_attention_interpret = False


class _Spy:
    """Counts calls through a module attribute, preserving behavior."""

    def __init__(self, mod, name):
        self.mod, self.name, self.calls = mod, name, 0
        self.orig = getattr(mod, name)

    def __enter__(self):
        def wrapped(*a, **k):
            self.calls += 1
            return self.orig(*a, **k)
        setattr(self.mod, self.name, wrapped)
        return self

    def __exit__(self, *exc):
        setattr(self.mod, self.name, self.orig)


def _train_lstm(mesh, steps=3, hidden=512, fused=False):
    """Build + train the benchmark LSTM (stacked_lstm2 inside) on a
    fixed corpus; returns (losses, final w of the first lstm kernel).
    mesh=None -> single-device Executor. Same init via fixed seed."""
    B, T, vocab = 64, 6, 120
    pt.reset()
    FLAGS.use_fused_rnn = fused
    try:
        words = pt.layers.data("words", shape=[-1], dtype=np.int32,
                               lod_level=1, append_batch_size=False)
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        logits = models.lstm_benchmark_net(
            words, vocab_size=vocab, emb_dim=128, hidden=hidden, max_len=T)
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        pt.default_startup_program().random_seed = 11
        exe = (pt.Executor() if mesh is None
               else pp.ParallelExecutor(mesh, shard_optimizer_state=True))
        exe.run(pt.default_startup_program())
        rng = np.random.RandomState(3)
        seqs = [rng.randint(0, vocab, (T,)).astype(np.int32)
                for _ in range(B)]
        feed = {"words": LoDArray.from_sequences(seqs, capacity=B * T,
                                                 max_seqs=B),
                "label": rng.randint(0, 2, (B, 1)).astype(np.int32)}
        losses = []
        for _ in range(steps):
            (l,) = exe.run(feed=feed, fetch_list=[loss])
            losses.append(float(l))
        w = None
        for k in pt.global_scope().keys():
            if "stacked_lstm" in k or "lstm" in k.lower():
                w = np.asarray(pt.global_scope().get(k))
                break
        assert w is not None, list(pt.global_scope().keys())
        return losses, w
    finally:
        FLAGS.use_fused_rnn = True


@pytest.mark.needs_shard_map
def test_fused_lstm_dp8_matches_single_device(fused_interpret):
    """dp8 mesh + fused LSTM kernels (in-window H=512) == single-device
    run of the SAME fused kernels, through training steps — isolates
    the mesh machinery (shard_map wrap + psum'd dW): a missing/wrong
    psum is off by ~dp x, not by rounding. Tolerance covers the f32
    reduction-order difference (per-shard dW partials summed by psum vs
    one full-batch einsum), which Adam amplifies step over step."""
    ref_losses, ref_w = _train_lstm(None, fused=True)
    mesh = pp.make_mesh((8,), ("dp",))
    with _Spy(pallas_kernels, "_lstm_pallas_raw") as spy:
        par_losses, par_w = _train_lstm(mesh, fused=True)
    assert spy.calls >= 1, "fused LSTM kernel did not dispatch under dp8"
    np.testing.assert_allclose(par_losses, ref_losses, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(par_w, ref_w, rtol=5e-3, atol=5e-3)


@pytest.mark.needs_shard_map
def test_fused_lstm_dp8_matches_scan_one_step(fused_interpret):
    """One step (before optimizer-state feedback compounds rounding):
    dp8 mesh + fused kernels matches the single-device XLA SCAN — the
    cross-formulation equivalence at tight tolerance."""
    ref_losses, _ = _train_lstm(None, steps=1, fused=False)
    mesh = pp.make_mesh((8,), ("dp",))
    par_losses, _ = _train_lstm(mesh, steps=1, fused=True)
    np.testing.assert_allclose(par_losses, ref_losses, rtol=2e-4, atol=2e-4)


@pytest.mark.needs_shard_map
def test_fused_lstm_dp_mp_mesh(fused_interpret):
    """Same equivalence under a 2-axis (dp4, mp2) mesh — the fused
    kernels shard over dp and replicate over mp."""
    ref_losses, ref_w = _train_lstm(None, fused=True)
    mesh = pp.make_mesh((4, 2), ("dp", "mp"))
    with _Spy(pallas_kernels, "_lstm_pallas_raw") as spy:
        par_losses, par_w = _train_lstm(mesh, fused=True)
    assert spy.calls >= 1, "fused LSTM kernel did not dispatch under dp4,mp2"
    np.testing.assert_allclose(par_losses, ref_losses, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(par_w, ref_w, rtol=5e-3, atol=5e-3)


def _train_nmt(mesh, steps=3, fused=False):
    B, S, vocab, H = 16, 10, 100, 128
    pt.reset()
    FLAGS.use_fused_attention = fused
    try:
        src = pt.layers.data("src", shape=[-1], dtype=np.int32,
                             lod_level=1, append_batch_size=False)
        trg_in = pt.layers.data("trg_in", shape=[-1], dtype=np.int32,
                                lod_level=1, append_batch_size=False)
        label = pt.layers.data("label", shape=[-1], dtype=np.int32,
                               lod_level=1, append_batch_size=False)
        logits = models.seq2seq_attention(
            src, trg_in, src_vocab=vocab, trg_vocab=vocab, emb_dim=H,
            enc_hidden=H, dec_hidden=H, src_max_len=S, trg_max_len=S)
        tok_loss = pt.layers.softmax_with_cross_entropy(logits, label)
        loss = pt.layers.mean(pt.layers.sequence_pool(tok_loss, "sum"))
        pt.optimizer.Adam(learning_rate=2e-3).minimize(loss)
        pt.default_startup_program().random_seed = 11
        exe = (pt.Executor() if mesh is None
               else pp.ParallelExecutor(mesh, shard_optimizer_state=True))
        exe.run(pt.default_startup_program())
        rng = np.random.RandomState(5)
        pack = lambda seqs: LoDArray.from_sequences(  # noqa: E731
            seqs, capacity=B * S, max_seqs=B)
        seqs = [rng.randint(2, vocab, (S,)).astype(np.int32)
                for _ in range(B)]
        feed = {"src": pack(seqs), "trg_in": pack(seqs),
                "label": pack(seqs)}
        losses = []
        for _ in range(steps):
            (l,) = exe.run(feed=feed, fetch_list=[loss])
            losses.append(float(l))
        w = np.asarray(pt.global_scope().get("s2s.dec_wa_dec")
                       if pt.global_scope().has("s2s.dec_wa_dec") else
                       next(pt.global_scope().get(k)
                            for k in pt.global_scope().keys()
                            if "dec" in k))
        return losses, w
    finally:
        FLAGS.use_fused_attention = True


@pytest.mark.needs_shard_map
def test_fused_decoder_dp2_matches_single_device(fused_interpret):
    """dp2 mesh + fused Bahdanau decoder == single-device fused decoder
    through training (psum'd dWx/dWh/dv/dWaDec/dbias correct), plus a
    one-step cross-check against the XLA scan."""
    ref_losses, ref_w = _train_nmt(None, fused=True)
    mesh = pp.make_mesh((2,), ("dp",), devices=jax.devices()[:2])
    bk.reset_dispatch_stats()
    par_losses, par_w = _train_nmt(mesh, fused=True)
    assert bk.dispatch_stats["fused_calls"] >= 1, bk.dispatch_stats
    np.testing.assert_allclose(par_losses, ref_losses, rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(par_w, ref_w, rtol=5e-3, atol=5e-3)
    scan_losses, _ = _train_nmt(None, steps=1, fused=False)
    mesh_losses, _ = _train_nmt(mesh, steps=1, fused=True)
    np.testing.assert_allclose(mesh_losses, scan_losses,
                               rtol=5e-4, atol=5e-4)


@pytest.mark.needs_shard_map
def test_bench_geometry_dispatches_fused_under_mesh(fused_interpret):
    """The bench-default NMT geometry (bs256, S=T=50, H=512, C=1024,
    bf16) keeps the FUSED path under a dp4 mesh: per-shard batch 64 is
    in-window, and the shard_map wrap traces end-to-end (fwd + bwd,
    jax.eval_shape — no compute). The day multi-chip hardware appears,
    BENCH_MESH=dp4 BENCH_MODEL=nmt runs exactly this path."""
    mesh = pp.make_mesh((4,), ("dp",), devices=jax.devices()[:4])
    B, S, T, E, C, A, H = 256, 50, 50, 512, 1024, 512, 512
    dt = jnp.bfloat16
    shapes = (
        jax.ShapeDtypeStruct((B, S, C), dt),
        jax.ShapeDtypeStruct((B, S, A), dt),
        jax.ShapeDtypeStruct((B, S), jnp.bool_),
        jax.ShapeDtypeStruct((T, B, E), dt),
        jax.ShapeDtypeStruct((T, B), jnp.float32),
        jax.ShapeDtypeStruct((B, H), dt),
        jax.ShapeDtypeStruct((H, A), dt),
        jax.ShapeDtypeStruct((A,), dt),
        jax.ShapeDtypeStruct((E + C, 3 * H), dt),
        jax.ShapeDtypeStruct((H, 3 * H), dt),
        jax.ShapeDtypeStruct((3 * H,), dt),
    )
    assert mesh_dispatch.local_batch(B) == B  # no mesh active yet
    with mesh_dispatch.active_mesh(mesh, "dp"):
        assert mesh_dispatch.local_batch(B) == 64
        assert bk.fused_decoder_eligible(
            mesh_dispatch.local_batch(B), S, A, C, dt)
        bk.reset_dispatch_stats()

        def loss(enc_b, ep, *rest):
            return jnp.sum(bk.fused_attention_decoder(
                enc_b, ep, *rest).astype(jnp.float32))

        jax.eval_shape(jax.grad(loss, argnums=(0, 1)), *shapes)
        assert bk.dispatch_stats["fused_calls"] >= 1, bk.dispatch_stats
        assert bk.dispatch_stats["scan_bwd"] >= 1, bk.dispatch_stats
    assert mesh_dispatch.current() is None


def test_local_batch_fallback_non_divisible(fused_interpret):
    """A batch the dp axis does not divide falls back to the scan (the
    eligibility sees local_batch == 0) instead of crashing in shard_map."""
    mesh = pp.make_mesh((8,), ("dp",))
    with mesh_dispatch.active_mesh(mesh, "dp"):
        assert mesh_dispatch.local_batch(60) == 0
        assert not pallas_kernels.lstm_supported(
            mesh_dispatch.local_batch(60), 512, "sigmoid", "tanh", "tanh",
            None)
        assert not bk.fused_decoder_eligible(
            mesh_dispatch.local_batch(60), 50, 512, 1024, jnp.bfloat16)


def test_fused_lstm_dp1_mesh(fused_interpret):
    """A dp=1 mesh (ParallelExecutor() on a single-device host) runs
    the fused kernels UNWRAPPED — the psum axis must not be bound then,
    or the backward traces a psum over an unbound axis name and crashes
    on the first step (caught in round-5 review)."""
    mesh = pp.make_mesh((1,), ("dp",), devices=jax.devices()[:1])
    losses, _ = _train_lstm(mesh, steps=2, fused=True)
    assert np.isfinite(losses).all() and losses[1] < losses[0], losses


@pytest.mark.needs_shard_map
def test_flash_attention_shard_maps_under_dp_mesh(monkeypatch):
    """The flash dispatcher wraps its kernel in shard_map under a dp
    mesh (kernel monkeypatched to the jnp reference — the real Mosaic
    kernel is TPU-only): per-shard local shapes, output parity vs
    unsharded, and gradients flow."""
    from paddle_tpu.ops import flash_ops

    calls = []

    def fake_kernel(q, k, v, causal):
        calls.append(tuple(q.shape))
        return flash_ops._reference(q, k, v, causal)

    monkeypatch.setattr(flash_ops, "_flash_kernel", fake_kernel)
    monkeypatch.setattr(flash_ops, "flash_eligible", lambda q, k=None: True)
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(16, 32, 4, 64) * 0.3, jnp.float32)
    q, k, v = mk(), mk(), mk()
    ref = flash_ops._reference(q, k, v, True)
    g_ref = jax.grad(lambda q: jnp.sum(
        flash_ops._reference(q, k, v, True) ** 2))(q)
    mesh = pp.make_mesh((8,), ("dp",))
    with mesh_dispatch.active_mesh(mesh, "dp"):
        out = flash_ops.flash_attention(q, k, v, causal=True)
        g = jax.grad(lambda q: jnp.sum(
            flash_ops.flash_attention(q, k, v, causal=True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=1e-4, atol=1e-4)
    assert calls and calls[0][0] == 16 // 8, calls  # per-shard batch
