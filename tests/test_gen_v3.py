"""Generation serving v3 (ISSUE 17): device-resident prefix cache +
speculative decoding.

Contracts under test:

- PREFIX CACHE — per-row raw-feed hashing (batch-neighbour
  independent), byte-budgeted LRU semantics, and the admission paths:
  admit-from-cache is BIT-IDENTICAL to admit-from-fresh-prefix in fp
  mode and bounded-delta in int8 mode; a retired slot re-admitted from
  a cached prefix reproduces the fresh result (slot reuse).
- SPECULATIVE DECODING — outputs and streamed token events are
  bit-identical to plain continuous decoding whether the draft is
  perfect (self-draft) or adversarial (a differently-seeded model):
  acceptance only moves throughput, never results.
- SATELLITES — the jitted prefix-PROGRAM cache is LRU-capped with
  evictions on the unified `pt_gen_prefix_evictions_total` counter;
  the draft-model sidecar in meta.json resolves relative to the
  artifact dir; fleetctl trace specs grow a digest-stable
  shared-prefix mix; all v3 gauges/counters are scrapeable from the
  unified /metrics registry.
"""

import json

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.serving import (
    BucketPolicy,
    PrefixCache,
    ServingEngine,
    prefix_row_key,
)

from tests.test_gen_serving import (  # noqa: F401  (fixtures re-exported)
    H,
    _build_chain_model,
    _build_gen_model,
    _chain_thr,
    chain_model_dir,
    gen_model_dir,
)


def _mk_engine(model_dir, name, **sched_kw):
    eng = ServingEngine(model_dir, policy=BucketPolicy(max_batch_size=8),
                        model_name=name)
    sched = eng.scheduler(**sched_kw)
    return eng, sched


@pytest.fixture(scope="module")
def gen_draft_dir(tmp_path_factory):
    """A differently-initialized copy of the GRU LM: same feeds, vocab,
    bos/eos — a legal draft whose proposals frequently DIVERGE from the
    target (the adversarial accept-pattern case)."""
    d = str(tmp_path_factory.mktemp("gen_draft"))
    pt.reset()
    pt.default_startup_program().random_seed = 11  # != target's 3
    _rebuild = __import__("tests.test_gen_serving",
                          fromlist=["_build_gen_model"])
    # _build_gen_model resets + reseeds internally; patch the seed by
    # rebuilding inline with a different startup seed
    from tests.test_gen_serving import BOS, EOS, K, T, V, E

    h0 = pt.layers.data("h0", shape=[-1, H], append_batch_size=False)
    gen = pt.layers.BeamSearchDecoder(
        beam_size=K, max_len=T, bos_id=BOS, eos_id=EOS)
    with gen.step():
        prev = gen.prev_ids()
        h_prev = gen.memory(init=h0)
        emb = pt.layers.embedding(prev, size=[V, E], param_attr="g_emb")
        h = pt.layers.fc(
            pt.layers.concat([emb, h_prev], axis=1), size=H, act="tanh",
            param_attr="g_w", bias_attr=pt.ParamAttr(name="g_b"))
        gen.update_memory(h_prev, h)
        gen.output_logits(pt.layers.fc(
            h, size=V, param_attr="g_wo",
            bias_attr=pt.ParamAttr(name="g_bo")))
    ids, scores, lengths = gen()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(d, ["h0"], [ids, scores, lengths])
    return d


# ---------------------------------------------------------------- hashing ---


def test_prefix_row_key_batch_neighbour_independence():
    """Row identity hashes the RAW row, so the same prompt in different
    batch compositions shares one cache entry."""
    a = {"x": np.arange(12, dtype=np.float32).reshape(3, 4)}
    b = {"x": a["x"][1:3]}  # a's row 1 is b's row 0
    assert prefix_row_key("fp", a, 1) == prefix_row_key("fp", b, 0)
    assert prefix_row_key("fp", a, 0) != prefix_row_key("fp", a, 1)
    # model identity is part of the key (two models, same prompt)
    assert prefix_row_key("fp", a, 0) != prefix_row_key("fp2", a, 0)
    # dtype matters even when bytes agree in value
    c = {"x": a["x"].astype(np.float64)}
    assert prefix_row_key("fp", a, 0) != prefix_row_key("fp", c, 0)
    # 0-d feeds hash whole (shared across rows)
    d0 = {"x": a["x"], "s": np.float32(2.5)}
    d1 = {"x": a["x"], "s": np.float32(3.5)}
    assert prefix_row_key("fp", d0, 0) != prefix_row_key("fp", d1, 0)


def test_prefix_cache_lru_byte_budget():
    pc = PrefixCache(100)
    assert pc.put("a", {"v": 1}, 40) == 0
    assert pc.put("b", {"v": 2}, 40) == 0
    assert pc.get("a") == {"v": 1}  # refreshes a: b is now LRU
    assert pc.put("c", {"v": 3}, 40) == 1  # evicts b
    assert pc.get("b") is None
    assert pc.get("a") is not None and pc.get("c") is not None
    assert len(pc) == 2 and pc.bytes == 80
    # an entry bigger than the whole budget is refused, evicting nothing
    assert pc.put("giant", {"v": 4}, 101) == 0
    assert pc.overflows == 1 and len(pc) == 2
    # re-put replaces bytes, not duplicates
    pc.put("a", {"v": 5}, 10)
    assert pc.bytes == 50 and pc.get("a") == {"v": 5}
    st = pc.stats()
    assert st["evictions"] == 1 and st["insertions"] == 4
    assert 0.0 < st["hit_rate"] < 1.0
    with pytest.raises(ValueError, match="positive"):
        PrefixCache(0)


# ------------------------------------------------------- cache admission ----


def test_fp_cache_hit_bit_identical_and_slot_reuse(gen_model_dir):
    """THE fp-cache contract: a cache-hit admission routes the SAME
    arrays through the SAME pool_admit as a fresh prefix, so results
    are bit-identical — including after slot retire/reuse cycles with
    max_slots=1 forcing every request through one recycled slot."""
    rng = np.random.RandomState(0)
    feeds = [{"h0": rng.randn(1, H).astype(np.float32)} for _ in range(3)]
    eng, sched = _mk_engine(gen_model_dir, "v3fp", max_slots=1,
                            prefix_cache_mb=4.0)
    try:
        fresh = [eng.generate(f, timeout_ms=60000) for f in feeds]  # misses
        again = [eng.generate(f, timeout_ms=60000) for f in feeds]  # hits
        for a, b in zip(fresh, again):
            np.testing.assert_array_equal(a["ids"], b["ids"])
            np.testing.assert_array_equal(a["scores"], b["scores"])
            np.testing.assert_array_equal(a["lengths"], b["lengths"])
        pc = sched.stats()["prefix_cache"]
        assert pc["insertions"] == 3
        assert pc["hits"] == 3 and pc["misses"] == 3
        # batch-mode oracle still agrees after cache-hit admissions
        want = eng.predict(feeds[0])
        got = eng.generate(feeds[0], timeout_ms=60000)
        np.testing.assert_array_equal(got["ids"], want[0])
        np.testing.assert_array_equal(got["scores"], want[1])
    finally:
        sched.stop()


def test_int8_cache_hit_bounded_delta(gen_model_dir):
    """int8-pooled entries admit with a bounded delta (per-tensor
    symmetric quant round-trip) and hold ~4x less bytes than fp."""
    rng = np.random.RandomState(1)
    feed = {"h0": rng.randn(1, H).astype(np.float32)}
    eng, sched = _mk_engine(gen_model_dir, "v3q", max_slots=2,
                            prefix_cache_mb=4.0, prefix_cache_quant="int8")
    try:
        fresh = eng.generate(feed, timeout_ms=60000)
        hit = eng.generate(feed, timeout_ms=60000)
        # int8 state round-trip: beam scores move by at most ~1e-2 on
        # this tiny model; the decode structure stays intact
        assert np.abs(fresh["scores"] - hit["scores"]).max() < 0.05
        assert fresh["ids"].shape == hit["ids"].shape
        q_bytes = sched.stats()["prefix_cache"]["bytes"]
    finally:
        sched.stop()
    eng2, sched2 = _mk_engine(gen_model_dir, "v3fp2", max_slots=2,
                              prefix_cache_mb=4.0)
    try:
        eng2.generate(feed, timeout_ms=60000)
        fp_bytes = sched2.stats()["prefix_cache"]["bytes"]
    finally:
        sched2.stop()
    # h0 is [H]=16 f32 = 64B fp vs 16B int8 + 4B scale = 20B (3.2x);
    # bound loosely so layout details don't make this flaky
    assert q_bytes < fp_bytes / 2


def test_cache_quant_knob_validated(gen_model_dir):
    eng = ServingEngine(gen_model_dir, model_name="v3bad")
    with pytest.raises(ValueError, match="prefix_cache_quant"):
        eng.scheduler(prefix_cache_mb=1.0, prefix_cache_quant="int4")


# -------------------------------------------------------- speculative -------


def test_speculative_self_draft_bit_identical(chain_model_dir):
    """Perfect-draft case (the model drafts for itself): outputs AND
    per-step token streams match plain continuous decoding exactly,
    while accept-rate accounting shows multi-token rounds."""
    feeds = [{"thr": _chain_thr(L)} for L in (6, 9, 12)]
    eng_p, sched_p = _mk_engine(chain_model_dir, "plain3", max_slots=2)
    try:
        want = [eng_p.generate(f, timeout_ms=60000) for f in feeds]
        plain_streams = []
        for f in feeds:
            h = sched_p.submit(f, timeout_ms=60000)
            plain_streams.append(
                [(e["step"], e["token"]) for e in h.events()
                 if e["event"] == "token"])
    finally:
        sched_p.stop()
    eng_s, sched_s = _mk_engine(chain_model_dir, "spec3", max_slots=2,
                                draft_model=chain_model_dir, draft_k=3)
    try:
        got = [eng_s.generate(f, timeout_ms=60000) for f in feeds]
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w["ids"], g["ids"])
            np.testing.assert_array_equal(w["scores"], g["scores"])
            np.testing.assert_array_equal(w["lengths"], g["lengths"])
        spec_streams = []
        for f in feeds:
            h = sched_s.submit(f, timeout_ms=60000)
            spec_streams.append(
                [(e["step"], e["token"]) for e in h.events()
                 if e["event"] == "token"])
        assert plain_streams == spec_streams
        st = sched_s.stats()["speculative"]
        assert st["verify_rounds_total"] > 0
        assert st["accepted_total"] > st["verify_rounds_total"], (
            "self-draft should accept >1 token/round on the chain model")
        # fewer host fences than tokens: the fusion win itself
        assert sched_s.syncs_total < sched_s.tokens_total
    finally:
        sched_s.stop()


def test_speculative_adversarial_draft_still_bit_identical(
        gen_model_dir, gen_draft_dir):
    """A draft with DIFFERENT weights mostly mis-proposes; every
    rejected draft must degrade to exactly one plain step — outputs
    stay bit-identical, accept rate just drops."""
    rng = np.random.RandomState(2)
    feeds = [{"h0": rng.randn(n, H).astype(np.float32)} for n in (1, 3)]
    eng_p, sched_p = _mk_engine(gen_model_dir, "plainadv", max_slots=4)
    try:
        want = [eng_p.generate(f, timeout_ms=60000) for f in feeds]
    finally:
        sched_p.stop()
    eng_s, sched_s = _mk_engine(gen_model_dir, "specadv", max_slots=4,
                                draft_model=gen_draft_dir, draft_k=4)
    try:
        got = [eng_s.generate(f, timeout_ms=60000) for f in feeds]
        for w, g in zip(want, got):
            np.testing.assert_array_equal(w["ids"], g["ids"])
            np.testing.assert_array_equal(w["scores"], g["scores"])
            np.testing.assert_array_equal(w["lengths"], g["lengths"])
        st = sched_s.stats()["speculative"]
        # every round advances >= 1 (the divergence-correcting step)
        assert st["accepted_total"] >= st["verify_rounds_total"]
    finally:
        sched_s.stop()


def test_speculative_with_prefix_cache_compose(chain_model_dir):
    """The two tentpole levers together: cached prefixes admit BOTH
    target and draft slot state, and repeated shared-prefix requests
    decode bit-identically through the cache-hit + verify path."""
    feed = {"thr": _chain_thr(8)}
    eng, sched = _mk_engine(chain_model_dir, "combo", max_slots=2,
                            draft_model=chain_model_dir, draft_k=3,
                            prefix_cache_mb=4.0)
    try:
        a = eng.generate(feed, timeout_ms=60000)
        b = eng.generate(feed, timeout_ms=60000)  # cache-hit admission
        np.testing.assert_array_equal(a["ids"], b["ids"])
        np.testing.assert_array_equal(a["scores"], b["scores"])
        st = sched.stats()
        assert st["prefix_cache"]["hits"] >= 1
        assert st["speculative"]["verify_rounds_total"] > 0
    finally:
        sched.stop()


def test_draft_model_validation(gen_model_dir, chain_model_dir,
                                dense_model_dir):
    eng = ServingEngine(gen_model_dir, model_name="vbad1")
    with pytest.raises(ValueError, match="no beam_search_group"):
        eng.scheduler(draft_model=dense_model_dir)
    eng2 = ServingEngine(gen_model_dir, model_name="vbad2")
    with pytest.raises(ValueError, match="feeds"):
        eng2.scheduler(draft_model=chain_model_dir)  # thr vs h0
    eng3 = ServingEngine(gen_model_dir, model_name="vbad3")
    with pytest.raises(ValueError, match="draft_k"):
        eng3.scheduler(draft_model=gen_model_dir, draft_k=0)


# needed by test_draft_model_validation
from tests.test_gen_serving import dense_model_dir  # noqa: F401,E402


def test_draft_sidecar_resolves_relative_to_artifact(tmp_path):
    """io.save_inference_model(draft_model=...) writes the sidecar;
    the scheduler resolves a relative dir against the artifact dir and
    drafts with it by default (no CLI knob needed)."""
    target = str(tmp_path / "target")
    _build_chain_model(target)
    draft = str(tmp_path / "target" / "draft")
    _build_chain_model(draft)
    # re-export the target WITH the sidecar (rebuild writes meta fresh)
    with open(target + "/meta.json") as f:
        meta = json.load(f)
    meta["draft_model"] = {"dir": "draft"}
    with open(target + "/meta.json", "w") as f:
        json.dump(meta, f)
    prog, _, _ = pt.io.load_inference_model(target, scope=pt.Scope())
    assert prog._draft_meta == {"dir": "draft"}
    eng, sched = _mk_engine(target, "sidecar", max_slots=2)
    try:
        assert sched._draft is not None
        assert sched._draft["dir"] == draft
        feed = {"thr": _chain_thr(7)}
        out = eng.generate(feed, timeout_ms=60000)
        assert out["ids"].shape[0] == 1
        assert sched.stats()["speculative"]["verify_rounds_total"] > 0
    finally:
        sched.stop()


def test_save_inference_model_writes_draft_sidecar(tmp_path):
    d = str(tmp_path / "m")
    pt.reset()
    x = pt.layers.data("x", shape=[4])
    pred = pt.layers.fc(x, size=2)
    pt.Executor().run(pt.default_startup_program())
    pt.io.save_inference_model(d, ["x"], [pred], draft_model="tiny")
    with open(d + "/meta.json") as f:
        assert json.load(f)["draft_model"] == {"dir": "tiny"}


# ------------------------------------------------- prefix-program LRU -------


def test_prefix_program_cache_lru_eviction(gen_model_dir):
    """Satellite 1: the jitted prefix-program cache is count-capped;
    novel padded shapes evict LRU programs and the unified
    pt_gen_prefix_evictions_total counter moves."""
    eng, sched = _mk_engine(gen_model_dir, "proglru", max_slots=8,
                            max_prefix_programs=1)
    rng = np.random.RandomState(3)
    try:
        before = sched.metrics.registry.counter_value(
            "pt_gen_prefix_evictions_total")
        # row counts 1 and 2 pad to different buckets -> 2 programs
        eng.generate({"h0": rng.randn(1, H).astype(np.float32)},
                     timeout_ms=60000)
        eng.generate({"h0": rng.randn(2, H).astype(np.float32)},
                     timeout_ms=60000)
        assert len(sched._prefix_cache) == 1  # capped
        assert sched.prefix_program_evictions >= 1
        after = sched.metrics.registry.counter_value(
            "pt_gen_prefix_evictions_total")
        assert after - before == sched.prefix_program_evictions
        # evicted shape still WORKS (re-trace, not an error)
        eng.generate({"h0": rng.randn(1, H).astype(np.float32)},
                     timeout_ms=60000)
    finally:
        sched.stop()
    with pytest.raises(ValueError, match="max_prefix_programs"):
        ServingEngine(gen_model_dir, model_name="proglru2").scheduler(
            max_prefix_programs=0)


# ---------------------------------------------------------- metrics ---------


def test_v3_metrics_scrapeable_from_unified_registry(chain_model_dir):
    """Acceptance: accept-rate + cache hit/miss/eviction families are
    present in the unified exposition after v3 traffic (and BEFORE any
    traffic for the declared counters)."""
    eng, sched = _mk_engine(chain_model_dir, "scrape", max_slots=2,
                            draft_model=chain_model_dir, draft_k=2,
                            prefix_cache_mb=2.0)
    try:
        text = sched.metrics.render()
        for fam in ("ptserving_gen_prefix_hits_total",
                    "ptserving_gen_prefix_misses_total",
                    "ptserving_gen_prefix_cache_evictions_total",
                    "ptserving_gen_draft_tokens_total",
                    "ptserving_gen_draft_accepted_total",
                    "ptserving_gen_verify_rounds_total",
                    "pt_gen_prefix_evictions_total"):
            assert fam in text, f"{fam} missing before traffic"
        feed = {"thr": _chain_thr(6)}
        eng.generate(feed, timeout_ms=60000)
        eng.generate(feed, timeout_ms=60000)
        text = sched.metrics.render()
        assert "ptserving_gen_prefix_cache_entries 1" in text
        assert "ptserving_gen_prefix_hit_rate 0.5" in text
        assert "ptserving_gen_accept_rate" in text
        assert "ptserving_gen_verify_round_seconds_bucket" in text
    finally:
        sched.stop()


# ------------------------------------------------------------ traces --------


def test_trace_shared_prefix_mix_and_digest_stability():
    """Satellite 2: shared_prefix_fraction tags ~that fraction of
    events with a prefix_group, and fraction=0 consumes ZERO extra
    randomness — pre-v3 traces replay byte-identically."""
    from paddle_tpu.fleetctl.traces import (TraceSpec, generate_trace,
                                            trace_digest)

    base = dict(duration_s=30.0, seed=7, base_rps=40.0,
                stream_fraction=0.1)
    old = generate_trace(TraceSpec(**base))
    new = generate_trace(TraceSpec(**base, shared_prefix_fraction=0.0))
    assert old == new
    assert trace_digest(old) == trace_digest(new)

    spec = TraceSpec(**base, shared_prefix_fraction=0.6, prefix_groups=3)
    ev = generate_trace(spec)
    assert ev == generate_trace(spec)  # replayable
    tagged = [e for e in ev if "prefix_group" in e]
    frac = len(tagged) / len(ev)
    assert 0.45 < frac < 0.75, f"60% mix drifted to {frac:.2f}"
    assert {e["prefix_group"] for e in tagged} <= {0, 1, 2}
    assert spec.describe()["shared_prefix_fraction"] == 0.6
    with pytest.raises(ValueError, match="shared_prefix_fraction"):
        TraceSpec(shared_prefix_fraction=1.5)
    with pytest.raises(ValueError, match="prefix_groups"):
        TraceSpec(prefix_groups=0)
