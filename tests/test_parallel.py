"""Parallelism tests on the simulated 8-device CPU mesh.

Reference analogue: in-process distributed tests (SURVEY.md §4.5 —
test_ParameterServer2.cpp runs servers+client in one process; nccl_op
tests run multi-GPU in one process). Here an 8-virtual-device mesh
exercises dp sharding, sharded embeddings (mp), and explicit collectives.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec

import paddle_tpu as pt
from paddle_tpu import parallel as pp


@pytest.fixture
def mesh8():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return pp.make_mesh((8,), ("dp",))


@pytest.fixture
def mesh42():
    return pp.make_mesh((4, 2), ("dp", "mp"))


def test_data_parallel_matches_single_device(mesh8):
    """Same program, same data: ParallelExecutor over 8 devices must equal

    the single-device Executor numerically (the reference's CPU-vs-GPU /
    single-vs-multi equivalence pattern, test_CompareTwoNets.cpp)."""
    def build():
        x = pt.layers.data("x", shape=[8])
        y = pt.layers.data("y", shape=[1])
        h = pt.layers.fc(x, size=16, act="relu",
                         param_attr=pt.ParamAttr(name="w1"),
                         bias_attr=pt.ParamAttr(name="b1"))
        pred = pt.layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w2"),
                            bias_attr=pt.ParamAttr(name="b2"))
        loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
        return loss

    rng = np.random.RandomState(0)
    xv = rng.randn(32, 8).astype(np.float32)
    yv = rng.randn(32, 1).astype(np.float32)

    # single device
    pt.reset()
    loss = build()
    prog_s = pt.default_main_program()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    snap = {k: np.asarray(pt.global_scope().get(k)).copy()
            for k in pt.global_scope().keys()}
    for _ in range(3):
        (ls,) = exe.run(prog_s, feed={"x": xv, "y": yv}, fetch_list=[loss])
    w_single = np.asarray(pt.global_scope().get("w1")).copy()

    # 8-device dp, identical init
    pt.reset()
    loss = build()
    prog_p = pt.default_main_program()
    for k, v in snap.items():
        pt.global_scope().set(k, v)
    pexe = pp.ParallelExecutor(mesh8)
    for _ in range(3):
        (lp,) = pexe.run(prog_p, feed={"x": xv, "y": yv}, fetch_list=[loss])
    w_par = np.asarray(pt.global_scope().get("w1"))

    np.testing.assert_allclose(ls, lp, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(w_single, w_par, rtol=1e-5, atol=1e-6)


def test_sharded_embedding_trains(mesh42):
    ids = pt.layers.data("ids", shape=[1], dtype=np.int32)
    label = pt.layers.data("label", shape=[1])
    emb = pp.sharded_embedding(ids, size=[64, 16], mesh_axis="mp",
                               param_attr=pt.ParamAttr(name="emb_w"))
    emb2 = pt.layers.reshape(emb, (-1, 16))
    pred = pt.layers.fc(emb2, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pexe = pp.ParallelExecutor(mesh42)
    rng = np.random.RandomState(0)
    ids_v = rng.randint(0, 64, size=(16, 1)).astype(np.int32)
    y_v = rng.randn(16, 1).astype(np.float32)
    losses = [
        float(pexe.run(feed={"ids": ids_v, "label": y_v}, fetch_list=[loss])[0])
        for _ in range(10)
    ]
    assert losses[-1] < losses[0]
    # table sharding survived the update loop
    w = pt.global_scope().get("emb_w")
    spec = w.sharding.spec if hasattr(w.sharding, "spec") else None
    assert spec == PartitionSpec("mp", None), spec


def test_ragged_feed_data_parallel(mesh8):
    """LSTM over a dp-sharded ragged batch runs and matches 1-device."""
    x = pt.layers.data("x", shape=[-1, 8], lod_level=1, append_batch_size=False)
    h = pt.layers.dynamic_lstm(x, size=8, max_len=8,
                               param_attr=pt.ParamAttr(name="lw"))
    pooled = pt.layers.sequence_pool(h, "last")
    out = pt.layers.mean(pooled)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    from paddle_tpu.core.lod import LoDArray

    rng = np.random.RandomState(0)
    seqs = [rng.randn(5, 8).astype(np.float32) for _ in range(8)]
    lod = LoDArray.from_sequences(seqs, capacity=64, max_seqs=8)
    (ref,) = exe.run(feed={"x": lod}, fetch_list=[out])
    pexe = pp.ParallelExecutor(mesh8)
    (par,) = pexe.run(feed={"x": lod}, fetch_list=[out])
    np.testing.assert_allclose(ref, par, rtol=1e-5, atol=1e-6)


@pytest.mark.needs_shard_map
def test_collectives_shard_map(mesh8):
    """psum / ring allreduce equivalence under shard_map."""
    x = jnp.arange(64, dtype=jnp.float32).reshape(8, 8)

    def f_psum(x):
        return pp.all_reduce(x, "dp")

    def f_ring(x):
        return pp.ring_all_reduce(x, "dp")

    s = PartitionSpec("dp", None)
    out1 = pp.shard_map_fn(f_psum, mesh8, (s,), s)(x)
    out2 = pp.shard_map_fn(f_ring, mesh8, (s,), s)(x)
    expect = np.tile(np.asarray(x).reshape(8, 1, 8).sum(axis=0), (8, 1))
    np.testing.assert_allclose(np.asarray(out1), expect.reshape(8, 8))
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out1), rtol=1e-5)


@pytest.mark.needs_shard_map
def test_reduce_scatter_allgather_roundtrip(mesh8):
    x = jnp.ones((64, 16), jnp.float32)  # per-shard [8, 16]

    def f(x):
        rs = pp.reduce_scatter(x, "dp", axis=0)  # -> [1, 16] per shard
        return pp.all_gather(rs, "dp", axis=0)  # -> [8, 16] per shard

    s = PartitionSpec("dp", None)
    out = pp.shard_map_fn(f, mesh8, (s,), s)(x)
    np.testing.assert_allclose(np.asarray(out), 8.0 * np.ones((64, 16)))


def test_sharded_optimizer_state_matches_replicated(mesh8):
    """ZeRO-1 via GSPMD (SURVEY.md §5.8): sharding Adam moments over dp

    must not change the training trajectory, and the state arrays must
    actually live sharded on the mesh."""
    def build():
        x = pt.layers.data("x", shape=[8])
        y = pt.layers.data("y", shape=[1])
        h = pt.layers.fc(x, size=16, act="relu",
                         param_attr=pt.ParamAttr(name="zw1"))
        pred = pt.layers.fc(h, size=1, param_attr=pt.ParamAttr(name="zw2"))
        loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.Adam(learning_rate=0.01).minimize(loss)
        return loss

    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(16, 8).astype(np.float32),
            "y": rng.randn(16, 1).astype(np.float32)}

    losses = {}
    for shard in (False, True):
        pt.reset()
        pt.default_startup_program().random_seed = 5
        loss = build()
        exe = pp.ParallelExecutor(mesh8, shard_optimizer_state=shard)
        base = pt.Executor()
        base.run(pt.default_startup_program())
        ls = []
        for _ in range(5):
            (l,) = exe.run(feed=feed, fetch_list=[loss])
            ls.append(float(l))
        losses[shard] = ls
        if shard:
            state_names = [
                v.name for v in pt.default_main_program().persistables()
                if getattr(v, "is_optimizer_state", False)
                and v.shape and v.shape[0] != -1 and v.shape[0] % 8 == 0
            ]
            assert state_names, "no shardable optimizer state found"
            m = pt.global_scope().get(state_names[0])
            spec = m.sharding.spec
            assert spec and spec[0] == "dp", (state_names[0], spec)
    np.testing.assert_allclose(losses[True], losses[False], rtol=1e-5)
