"""Fused conv+BN protocol parity (ops/fused_conv_ops.py).

Reference: the cuDNN fused conv path (gserver/layers/CudnnConvBaseLayer.cpp)
— the reference's conv hot path is never naive composed ops. Here the
fused raw-stats formulation (Pallas 1x1-conv kernels with BN
prologue/epilogue) must match the unfused conv2d+batch_norm formulation:
forward losses, gradients, running-stat updates, and checkpoint parameter
names (so train-mode fused checkpoints load into eval-mode unfused
graphs).
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.flags import FLAGS


def _build_tower(fused, batch=8, hw=8, cin=16, ch=8, seed=5):
    """Two stacked bottleneck blocks (one with projection+stride) ending
    in a mean loss; returns (loss_var, feed, param_names)."""
    pt.reset()
    FLAGS.use_fused_conv = fused
    from paddle_tpu.models.image import _bottleneck

    pt.default_startup_program().random_seed = seed
    x = pt.layers.data("x", shape=[hw, hw, cin])
    t = _bottleneck(x, ch, stride=2, is_test=False, data_format="NHWC",
                    name="blk1")
    t = _bottleneck(t, ch, stride=1, is_test=False, data_format="NHWC",
                    name="blk2")
    loss = pt.layers.mean(t)
    rng = np.random.RandomState(0)
    feed = {"x": rng.randn(batch, hw, hw, cin).astype(np.float32)}
    return loss, feed


def _train_steps(fused, steps=3, **kw):
    loss, feed = _build_tower(fused, **kw)
    opt = pt.optimizer.Momentum(learning_rate=0.05, momentum=0.9)
    opt.minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    losses = []
    for _ in range(steps):
        (l,) = exe.run(feed=feed, fetch_list=[loss])
        losses.append(float(l))
    scope = pt.core.executor.global_scope()
    params = {}
    for name in sorted(pt.default_main_program().global_block().vars):
        if name not in scope.vars or not getattr(
                pt.default_main_program().global_block().var(name),
                "persistable", False):
            continue
        # optimizer accumulators carry an auto-counter prefix that
        # legitimately differs between builds; key them by param suffix
        key = ("velocity." + name.split(".velocity.", 1)[1]
               if ".velocity." in name else name)
        if key.endswith(".lr"):
            continue
        params[key] = np.asarray(scope.vars[name])
    return losses, params


def test_fused_matches_unfused_training():
    """3 momentum steps: identical init -> losses, every parameter, and
    every BN running stat agree between the two formulations."""
    losses_u, params_u = _train_steps(fused=False)
    losses_f, params_f = _train_steps(fused=True)
    np.testing.assert_allclose(losses_f, losses_u, rtol=2e-4, atol=2e-5)
    assert set(params_f) == set(params_u), (
        "checkpoint name parity broken: "
        f"{set(params_f) ^ set(params_u)}")
    for name in params_u:
        np.testing.assert_allclose(
            params_f[name], params_u[name], rtol=5e-3, atol=5e-4,
            err_msg=name)


def test_fused_train_checkpoint_loads_into_eval_graph(tmp_path):
    """Train fused (NHWC train graph), save params, rebuild is_test=True
    (always unfused) and load — names must line up and eval must run."""
    loss, feed = _build_tower(fused=True)
    pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    exe.run(feed=feed, fetch_list=[loss])
    pt.io.save_params(str(tmp_path), pt.default_main_program())

    pt.reset()
    from paddle_tpu.models.image import _bottleneck

    x = pt.layers.data("x", shape=[8, 8, 16])
    t = _bottleneck(x, 8, stride=2, is_test=True, data_format="NHWC",
                    name="blk1")
    t = _bottleneck(t, 8, stride=1, is_test=True, data_format="NHWC",
                    name="blk2")
    out = pt.layers.mean(t)
    exe2 = pt.Executor()
    exe2.run(pt.default_startup_program())
    pt.io.load_params(str(tmp_path), pt.default_main_program())
    (v,) = exe2.run(feed=feed, fetch_list=[out])
    assert np.isfinite(v)


def test_pallas_kernel_interpret_parity():
    """The actual Pallas kernel (interpret mode on CPU), fwd + custom-VJP
    grads, vs the jnp fallback on the same eligible shapes."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops.fused_conv_ops import _fused_fn, _jnp_fused

    n, cin, cout = 64, 128, 128
    if jax.default_backend() == "tpu":
        pytest.skip("interpret-mode parity is the CPU-suite variant")
    rng = np.random.RandomState(1)
    x = jnp.asarray(rng.randn(n, cin), jnp.float32)
    w = jnp.asarray(rng.randn(cin, cout) * 0.1, jnp.float32)
    pm = jnp.asarray(rng.randn(cin) * 0.1, jnp.float32)
    pi = jnp.asarray(1.0 + 0.1 * rng.rand(cin), jnp.float32)
    ps = jnp.asarray(1.0 + 0.1 * rng.randn(cin), jnp.float32)
    pb = jnp.asarray(0.1 * rng.randn(cin), jnp.float32)

    for prologue in (False, True):
        f = _fused_fn(prologue, True, True)  # interpret=True

        def loss_k(x, w, pm, pi, ps, pb):
            y, s, sq = f(x, w, pm, pi, ps, pb)
            return (jnp.sum(y * y) * 1e-3 + jnp.sum(s * 3.0)
                    + jnp.sum(sq) * 1e-4)

        def loss_j(x, w, pm, pi, ps, pb):
            y, s, sq = _jnp_fused(x, w, pm, pi, ps, pb, prologue, True)
            return (jnp.sum(y * y) * 1e-3 + jnp.sum(s * 3.0)
                    + jnp.sum(sq) * 1e-4)

        yk = f(x, w, pm, pi, ps, pb)
        yj = _jnp_fused(x, w, pm, pi, ps, pb, prologue, True)
        for a, b in zip(yk, yj):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-5, atol=2e-5)
        gk = jax.grad(loss_k, argnums=(0, 1, 2, 3, 4, 5))(
            x, w, pm, pi, ps, pb)
        gj = jax.grad(loss_j, argnums=(0, 1, 2, 3, 4, 5))(
            x, w, pm, pi, ps, pb)
        for a, b in zip(gk, gj):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-4)


def test_resnet_builds_fused_nhwc():
    """resnet_imagenet NHWC train graph contains fused_conv_bn ops; the
    NCHW and eval graphs contain none."""
    pt.reset()
    FLAGS.use_fused_conv = True
    from paddle_tpu import models

    x = pt.layers.data("img", shape=[224, 224, 3])
    models.resnet_imagenet(x, class_dim=10, data_format="NHWC")
    ops = [op.type for op in pt.default_main_program().global_block().ops]
    assert ops.count("fused_conv_bn") == 36  # 16 bottlenecks x 2 + 4 proj
    assert ops.count("bn_stats") == 16

    pt.reset()
    x = pt.layers.data("img", shape=[3, 224, 224])
    models.resnet_imagenet(x, class_dim=10, data_format="NCHW")
    ops = [op.type for op in pt.default_main_program().global_block().ops]
    assert ops.count("fused_conv_bn") == 0
