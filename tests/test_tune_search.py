"""Autotuner v2 guided search (paddle_tpu/tune/search.py).

The ISSUE-10 acceptance bar, proven on the injectable oracle (the same
protocol the real compile+measure loop implements — harness.py refuses
to time off-TPU, which is exactly why the searcher takes the oracle as
a parameter): guided search reaches >= 95% of exhaustive-search quality
while timing <= 40% of the candidate space, the successive-halving
mechanics stop early on a stable leader, and a config that fails the
oracle (numerics) can never win.
"""

import math

import pytest

from paddle_tpu.tune import harness, overrides, search, space
from paddle_tpu.tune import cache as tcache

# spaces large enough that the 40% budget actually prunes (flash is the
# quadratic one the guided search exists for)
BIG_CASES = [
    ("flash_attention", {"Tq": 2048, "Tk": 2048}),   # 25 candidates
    ("flash_attention", {"Tq": 4096, "Tk": 4096}),   # 25
    ("flash_attention", {"Tq": 8192, "Tk": 8192}),   # 25
    ("flash_attention", {"Tq": 4096, "Tk": 1024}),   # 20
    ("fused_conv", {"n": 50176, "cin": 64, "cout": 256}),   # 10
]


# ------------------------------------------------------- cost model ------
def test_predicted_cost_finite_and_ranking_total():
    """The model scores every legal candidate of every family with a
    finite positive cost, and rank_candidates is a permutation of the
    candidate set (nothing dropped, nothing invented)."""
    cases = BIG_CASES + [
        ("bahdanau_attention", {"B": 256, "Sp": 64, "A": 512, "C": 512}),
        ("fused_lstm", {"B": 128, "H": 512}),
        ("fused_gru", {"B": 128, "H": 384}),
    ]
    for fam_name, params in cases:
        fam = space.get_family(fam_name)
        norm = fam.normalize(params, "bfloat16")
        cands = fam.candidates(norm)
        ranked = search.rank_candidates(fam_name, params, "bfloat16")
        assert sorted(map(search.config_key, ranked)) == \
            sorted(map(search.config_key, cands))
        for cfg in cands:
            c = search.predicted_cost(fam_name, norm, cfg)
            assert math.isfinite(c) and c > 0, (fam_name, cfg, c)
        # deterministic: same call, same order
        assert ranked == search.rank_candidates(fam_name, params,
                                                "bfloat16")


def test_cost_model_prefers_measured_bahdanau_winner():
    """At the NMT shapes the measured winner is bblk=8 (the 256k-vs-217k
    tok/s sweep the tuner was built around): the VMEM-pressure term must
    rank it above the budget-saturating bblk=16."""
    norm = {"B": 256, "Sp": 64, "A": 512, "C": 512, "dtype": "bfloat16"}
    ranked = search.rank_candidates(
        "bahdanau_attention", {"B": 256, "Sp": 64, "A": 512, "C": 512},
        "bfloat16")
    assert ranked[0] == {"bblk": 8}, ranked


# ------------------------------------------------- search mechanics ------
def test_guided_search_respects_probe_budget():
    for fam_name, params in BIG_CASES:
        ranked = search.rank_candidates(fam_name, params, "bfloat16")
        oracle = search.SimulatedOracle(fam_name, params, "bfloat16")
        res = search.guided_search(ranked, oracle)
        n = len(ranked)
        assert res.n_candidates == n
        assert res.n_timed == oracle.timed
        assert res.n_timed <= max(3, int(0.4 * n))
        assert res.timed_fraction <= 0.4 + 1e-9, (fam_name, params,
                                                  res.timed_fraction)


def test_guided_search_stops_early_on_stable_leader():
    """A surface with one clear winner: after two rungs with the same
    leader the search stops without running the last rung over the
    whole survivor set."""
    cands = [{"x": i} for i in range(20)]
    calls = []

    def oracle(cfg, iters):
        calls.append((cfg["x"], iters))
        return 1.0 + cfg["x"]  # candidate 0 always wins

    res = search.guided_search(cands, oracle, rungs=(1, 3, 7, 15))
    assert res.best == {"x": 0}
    assert res.stopped_early
    assert res.rungs_run == 2  # leader stable after the second rung
    assert res.n_timed == 8  # floor(0.4 * 20)


def test_guided_search_drops_failed_candidates():
    """oracle -> +inf marks numerics failure: the config is out
    immediately and can never be the winner; all-inf raises."""
    cands = [{"x": i} for i in range(10)]

    def oracle(cfg, iters):
        return float("inf") if cfg["x"] == 0 else float(cfg["x"])

    res = search.guided_search(cands, oracle)
    assert res.best == {"x": 1}
    with pytest.raises(RuntimeError, match="every probed candidate"):
        search.guided_search(cands, lambda c, i: float("inf"))


def test_simulated_oracle_deterministic():
    o1 = search.SimulatedOracle("flash_attention",
                                {"Tq": 2048, "Tk": 2048}, "bfloat16",
                                seed=3)
    o2 = search.SimulatedOracle("flash_attention",
                                {"Tq": 2048, "Tk": 2048}, "bfloat16",
                                seed=3)
    cfg = {"block_q": 512, "block_k": 512}
    assert o1(cfg, 1) == o2(cfg, 1)
    # a different seed is a different surface
    o3 = search.SimulatedOracle("flash_attention",
                                {"Tq": 2048, "Tk": 2048}, "bfloat16",
                                seed=4)
    assert o3(cfg, 1) != o1(cfg, 1)


# ---------------------------------------------- quality acceptance ------
def test_guided_reaches_95pct_of_exhaustive_at_40pct_probes():
    """THE acceptance property, over every big-space case and 8
    device-quirk seeds: the guided winner's TRUE time is within 5% of
    the exhaustive-search optimum, having timed at most 40% of the
    space. Deterministic (SimulatedOracle is seeded sha256, no RNG
    state)."""
    for fam_name, params in BIG_CASES:
        fam = space.get_family(fam_name)
        norm = fam.normalize(params, "bfloat16")
        cands = fam.candidates(norm)
        ranked = search.rank_candidates(fam_name, params, "bfloat16")
        for seed in range(8):
            oracle = search.SimulatedOracle(fam_name, params, "bfloat16",
                                            seed=seed)
            res = search.guided_search(ranked, oracle)
            _, true_best_s = oracle.exhaustive_best(cands)
            quality = true_best_s / oracle.true_time(res.best)
            assert quality >= 0.95, (fam_name, params, seed, quality)
            assert res.timed_fraction <= 0.4 + 1e-9


# ------------------------------------------- harness integration ------
@pytest.fixture
def tmp_table(tmp_path):
    path = str(tmp_path / "tuned.json")
    overrides.set_table_path(path)
    yield path
    overrides.reset()


def test_tune_case_guided_with_injected_oracle(tmp_table):
    """tune_case(mode="guided", oracle=...) never compiles anything
    (the injected oracle IS the timing source), prunes the space, and
    records the winner with provenance "measured"."""
    params = {"Tq": 2048, "Tk": 2048}
    oracle = search.SimulatedOracle("flash_attention", params, "bfloat16",
                                    seed=0)
    t = overrides.table()
    rep = harness.tune_case("flash_attention", params, "bfloat16",
                            table=t, iters=7, oracle=oracle)
    s = rep["search"]
    assert s["mode"] == "guided"
    assert s["timed"] <= int(0.4 * s["candidates"])
    assert any(not r["timed"] for r in rep["rows"])  # space was pruned
    # winner is in the table under the runtime key, stamped measured
    cfg = t.get("flash_attention", params, "bfloat16")
    assert cfg == rep["best"]
    key = tcache.entry_key("flash_attention", tcache.make_sig(params),
                           "bfloat16", tcache.device_kind())
    meta = t.entries[key]["meta"]
    assert meta["provenance"] == "measured"
    assert meta["updated_at"] > 0


def test_tune_case_exhaustive_mode_times_everything(tmp_table):
    params = {"Tq": 2048, "Tk": 2048}
    oracle = search.SimulatedOracle("flash_attention", params, "bfloat16",
                                    seed=0)
    rep = harness.tune_case("flash_attention", params, "bfloat16",
                            iters=3, mode="exhaustive", oracle=oracle)
    assert rep["search"] == {"mode": "exhaustive",
                             "candidates": 25, "timed": 25,
                             "timed_fraction": 1.0}
    assert all(r["timed"] for r in rep["rows"])
    assert "speedup_vs_default" in rep
    # on the same surface, exhaustive and guided agree on the winner
    # whenever the guided probe set contains the true best
    oracle2 = search.SimulatedOracle("flash_attention", params,
                                     "bfloat16", seed=0)
    rep_g = harness.tune_case("flash_attention", params, "bfloat16",
                              iters=3, oracle=oracle2)
    assert oracle2.true_time(rep_g["best"]) <= \
        1.0 / 0.95 * oracle2.true_time(rep["best"])


def test_tune_case_guided_small_space_times_all(tmp_table):
    """min_probes floors tiny spaces: a 2-candidate bahdanau case is
    fully swept even in guided mode (nothing to prune)."""
    params = {"B": 16, "Sp": 16, "A": 128, "C": 128}
    oracle = search.SimulatedOracle("bahdanau_attention", params,
                                    "float32", seed=0)
    rep = harness.tune_case("bahdanau", params, "float32", iters=2,
                            oracle=oracle)
    assert rep["search"]["timed"] == rep["search"]["candidates"] == 2
    assert {r["config"]["bblk"] for r in rep["rows"]} == {8, 16}
