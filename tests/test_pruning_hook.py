"""Static pruning updater hook tests.

Reference: paddle/parameter/ParameterUpdaterHook.cpp:39 StaticPruningHook —
a magnitude mask generated at init time and re-applied after every
optimizer update, exposed through the Gen-1
ParameterAttribute(update_hooks=...) seam (here
ParamAttr(update_hooks=[StaticPruningHook(...)])).
"""

import numpy as np

import paddle_tpu as pt


def _build(sparsity):
    x = pt.layers.data("x", shape=[16])
    y = pt.layers.data("y", shape=[1])
    h = pt.layers.fc(
        x, size=32, act="tanh",
        param_attr=pt.ParamAttr(
            name="w_pruned",
            update_hooks=[pt.StaticPruningHook(sparsity_ratio=sparsity)],
        ),
        bias_attr=False,
    )
    pred = pt.layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w_dense"),
                        bias_attr=False)
    return pt.layers.mean(pt.layers.square_error_cost(pred, y))


def _feed(step):
    rng = np.random.RandomState(100 + step)
    return {"x": rng.randn(32, 16).astype(np.float32),
            "y": rng.randn(32, 1).astype(np.float32)}


def test_static_pruning_survives_training():
    pt.reset()
    pt.default_startup_program().random_seed = 7
    loss = _build(sparsity=0.75)
    pt.optimizer.Adam(learning_rate=0.02).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    scope = pt.core.executor.global_scope()
    mask0 = np.asarray(scope.get("w_pruned@PRUNE_MASK"))
    n = mask0.size
    # mask itself hits the requested sparsity (ties can only zero more)
    assert (mask0 == 0).sum() >= int(0.75 * n)

    losses = []
    for s in range(12):
        (l,) = exe.run(feed=_feed(s), fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < losses[0], losses

    w = np.asarray(scope.get("w_pruned"))
    # every masked weight is STILL exactly zero after 12 Adam updates
    # (adam moments would drift them off zero without the hook)
    assert np.all(w[mask0 == 0] == 0.0)
    # and the surviving weights trained (nonzero, changed)
    assert np.count_nonzero(w[mask0 == 1]) == (mask0 == 1).sum()
    # the mask is static: zero-set after training == zero-set at init
    np.testing.assert_array_equal(
        np.asarray(scope.get("w_pruned@PRUNE_MASK")), mask0)
    # the dense companion param was not pruned
    assert np.count_nonzero(np.asarray(scope.get("w_dense"))) > 0


def test_pruning_mask_threshold_semantics():
    """Mask zeroes exactly the smallest-|w| fraction (up to ties)."""
    pt.reset()
    pt.default_startup_program().random_seed = 11
    _build(sparsity=0.5)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.executor.global_scope()
    w = np.asarray(scope.get("w_pruned"))
    mask = np.asarray(scope.get("w_pruned@PRUNE_MASK"))
    kept = np.abs(w[mask == 1])
    dropped = np.abs(w[mask == 0])
    assert kept.min() > dropped.max()  # magnitude criterion, no mixing


def test_pruning_masks_param_at_startup():
    """Reference StaticPruningHook::init dotMuls the mask into the param
    immediately — the very first forward must already be pruned, before
    any optimizer step."""
    pt.reset()
    pt.default_startup_program().random_seed = 13
    _build(sparsity=0.75)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.executor.global_scope()
    w = np.asarray(scope.get("w_pruned"))
    mask = np.asarray(scope.get("w_pruned@PRUNE_MASK"))
    assert np.all(w[mask == 0] == 0.0)


def test_pruning_exact_k_under_ties():
    """A constant-magnitude init ties every |w| at the threshold; the
    reference selects exactly nonZeroNum survivors (partial_sort on
    indices), never masking the whole parameter."""
    pt.reset()
    x = pt.layers.data("x", shape=[16])
    h = pt.layers.fc(
        x, size=32, act=None,
        param_attr=pt.ParamAttr(
            name="w_tied",
            initializer=pt.initializer.Constant(0.5),
            update_hooks=[pt.StaticPruningHook(sparsity_ratio=0.75)],
        ),
        bias_attr=False,
    )
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.core.executor.global_scope()
    mask = np.asarray(scope.get("w_tied@PRUNE_MASK"))
    assert (mask == 0).sum() == int(round(0.75 * mask.size))
    assert (mask == 1).sum() == mask.size - int(round(0.75 * mask.size))
