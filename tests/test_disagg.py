"""Disaggregated prefill/decode serving (ISSUE 18).

The contract under test: a prefill replica runs ONLY the bucketed
prefix program and emits the request's decode boot state as a
self-describing handoff payload; a decode replica validates and admits
the shipped state through the UNCHANGED `pool_admit` dynamic-update
path — so per-request results are BIT-IDENTICAL to monolithic serving
by construction, at every bucket size. Around that core: the wire
format round-trips (int8 packing bounded by the per-row quant error),
schema-identity mismatches fail at the /admit boundary with a typed
409 naming the rollout fix (never a shape crash in the pool), the
router scores the two replica classes on their own signals, the
dispatcher's failure semantics (same-payload decode failover, ONE
re-prefill on class-wide refusal, then a retryable 503) hold over real
HTTP, one warm pool serves both classes (deficit promotion), the two
phase autoscalers coexist under distinct metric families, the bench
trace mix is digest-stable, and one armed Perfetto capture shows the
prefill → transfer → decode span chain linked by X-PT-Request-Id.
"""

import ast
import json
import struct
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.fleetctl import SimReplica
from paddle_tpu.fleetctl.autoscaler import Autoscaler
from paddle_tpu.fleetctl.traces import (TraceSpec, generate_trace,
                                        trace_digest)
from paddle_tpu.obs import metrics as obs_metrics
from paddle_tpu.obs import promparse
from paddle_tpu.obs import trace as obs_trace
from paddle_tpu.serving import (BucketPolicy, ModelRegistry,
                                ServingEngine, make_server)
from paddle_tpu.serving.disagg import (DisaggDispatcher, DisaggFleet,
                                       HandoffError, HandoffSchemaError,
                                       PhaseFleet, make_phase_autoscalers,
                                       pack_handoff, payload_schema,
                                       unpack_handoff, validate_handoff)
from paddle_tpu.serving.router import (NoReplicaError, Router,
                                       make_router_server)
from paddle_tpu.serving.server import REQUEST_ID_HEADER

V, E, H = 12, 8, 16
BOS, EOS = 0, 1
K, T = 3, 6

# ---------------------------------------------------------------- fixtures --


def _build_gen_model(dirname: str) -> None:
    """Tiny GRU-ish LM decoder (same shape as test_gen_serving.py),
    saved with the generation meta sidecar + schema identity."""
    pt.reset()
    pt.default_startup_program().random_seed = 3
    h0 = pt.layers.data("h0", shape=[-1, H], append_batch_size=False)
    gen = pt.layers.BeamSearchDecoder(beam_size=K, max_len=T,
                                      bos_id=BOS, eos_id=EOS)
    with gen.step():
        prev = gen.prev_ids()
        h_prev = gen.memory(init=h0)
        emb = pt.layers.embedding(prev, size=[V, E], param_attr="g_emb")
        h = pt.layers.fc(
            pt.layers.concat([emb, h_prev], axis=1), size=H, act="tanh",
            param_attr="g_w", bias_attr=pt.ParamAttr(name="g_b"))
        gen.update_memory(h_prev, h)
        gen.output_logits(pt.layers.fc(
            h, size=V, param_attr="g_wo",
            bias_attr=pt.ParamAttr(name="g_bo")))
    ids, scores, lengths = gen()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(dirname, ["h0"], [ids, scores, lengths])


@pytest.fixture(scope="module")
def gen_model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("disagg_gen"))
    _build_gen_model(d)
    return d


def _engine(model_dir, name, **sched_kw):
    eng = ServingEngine(model_dir, policy=BucketPolicy(max_batch_size=8),
                        model_name=name)
    return eng, eng.scheduler(**sched_kw)


def _schema():
    return {"schema_version": 1, "state_fingerprint": "a" * 16}


def _wait_until(pred, timeout=10.0, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


def _post(url, payload, headers=None, timeout=60):
    body = (payload if isinstance(payload, bytes)
            else json.dumps(payload).encode())
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(headers or {})
    req = urllib.request.Request(url, data=body, headers=hdrs)
    return urllib.request.urlopen(req, timeout=timeout)


# ------------------------------------------------------------ wire format --


def test_pack_unpack_roundtrip_exact():
    rng = np.random.RandomState(0)
    boots = (rng.randn(3, 16).astype(np.float32),
             np.full((3, 1), 7, np.int32))
    pes = (rng.randn(3, 4).astype(np.float32),)
    blob = pack_handoff(boots, pes, _schema(), "default",
                        request_id="r1")
    assert blob.startswith(b"PTHO1")
    header, got_b, got_p = unpack_handoff(blob)
    assert header["model"] == "default"
    assert header["rows"] == 3
    assert header["request_id"] == "r1"
    assert header["quant"] is None
    assert header["state_fingerprint"] == "a" * 16
    for want, got in zip(boots + pes, got_b + got_p):
        assert want.dtype == got.dtype
        np.testing.assert_array_equal(want, got)


def test_int8_packing_cuts_bytes_with_per_row_bounded_error():
    """int8 packing reuses the scheduler's q_rows recipe per ROW:
    absmax/127 scale, so dequant error is bounded by scale/2
    elementwise — and float buffers drop 4x on the wire (int state
    rides raw, byte-exact)."""
    rng = np.random.RandomState(1)
    boots = (rng.randn(4, 64).astype(np.float32) * 3.0,
             np.arange(4, dtype=np.int32).reshape(4, 1))
    raw = pack_handoff(boots, (), _schema(), "m")
    q = pack_handoff(boots, (), _schema(), "m", quant="int8")
    assert len(q) < 0.6 * len(raw)
    header, got_b, _ = unpack_handoff(q)
    assert header["quant"] == "int8"
    deq = got_b[0]
    assert deq.dtype == np.float32
    scale = np.abs(boots[0]).max(axis=1, keepdims=True) / 127.0
    assert np.all(np.abs(deq - boots[0]) <= 0.5 * scale + 1e-6)
    np.testing.assert_array_equal(got_b[1], boots[1])


def test_unpack_rejects_malformed_payloads():
    blob = pack_handoff((np.ones((1, 2), np.float32),), (), _schema(),
                        "m")
    with pytest.raises(HandoffError, match="magic"):
        unpack_handoff(b"nope" + blob)
    with pytest.raises(HandoffError):
        unpack_handoff(blob[:-3])  # truncated buffer
    with pytest.raises(HandoffError, match="trailing"):
        unpack_handoff(blob + b"xx")
    with pytest.raises(HandoffError, match="row"):
        pack_handoff((np.ones((1, 2), np.float32),
                      np.ones((2, 2), np.float32)), (), _schema(), "m")
    with pytest.raises(HandoffError, match="quant"):
        pack_handoff((np.ones((1, 2), np.float32),), (), _schema(),
                     "m", quant="int4")


def test_schema_mismatch_names_the_rollout_command():
    """Satellite 1: a mixed-version fleet fails at admission with a
    TYPED error whose message names the one-command fix."""
    meta = {"schema_version": 1, "state_fingerprint": "a" * 16,
            "state": [], "per_example": []}
    validate_handoff(_schema(), meta)  # matching identity passes
    with pytest.raises(HandoffSchemaError, match="fleetctl rollout"):
        validate_handoff({"schema_version": 1,
                          "state_fingerprint": "b" * 16}, meta)
    with pytest.raises(HandoffSchemaError, match="fleetctl rollout"):
        validate_handoff({"schema_version": 2,
                          "state_fingerprint": "a" * 16}, meta)
    with pytest.raises(HandoffError, match="generation"):
        payload_schema({})


def test_meta_sidecar_carries_schema_identity(gen_model_dir):
    """Satellite 1: save_inference_model stamps the DecodeState schema
    version + state fingerprint into the generation sidecar, and the
    fingerprint is a pure function of the state layout (NOT the
    program fingerprint — a retrained same-geometry artifact must
    hand off mid-rollout)."""
    with open(gen_model_dir + "/meta.json") as f:
        g = json.load(f)["generation"]
    assert g["schema_version"] == pt.io.GENERATION_SCHEMA_VERSION
    assert g["state_fingerprint"] == \
        pt.io.generation_state_fingerprint(g)
    # identity depends only on geometry + state specs, not on the
    # weights: recompute from the layout keys alone
    trimmed = {k: g[k] for k in ("beam_size", "max_len", "bos_id",
                                 "eos_id", "state", "per_example")}
    assert pt.io.generation_state_fingerprint(trimmed) == \
        g["state_fingerprint"]


# ----------------------------------------------- scheduler bit-identity ----


def test_handoff_bit_identical_to_monolithic(gen_model_dir):
    """THE acceptance property: prefill on one engine → serialize →
    unpack → admit on ANOTHER engine is bit-identical to a monolithic
    generate on the admitting engine, across bucket sizes."""
    pf_eng, pf_sched = _engine(gen_model_dir, "pf_bit", max_slots=4)
    de_eng, de_sched = _engine(gen_model_dir, "de_bit", max_slots=4)
    rng = np.random.RandomState(0)
    try:
        for n in (1, 2, 3, 5):
            feed = {"h0": rng.randn(n, H).astype(np.float32)}
            want = de_eng.generate(feed, timeout_ms=60000)
            boots, pes = pf_sched.prefill(feed)
            blob = pack_handoff(
                boots, pes, payload_schema(pf_eng.generation_meta),
                "default")
            header, b2, p2 = unpack_handoff(blob)
            validate_handoff(header, de_eng.generation_meta)
            got = de_sched.submit_handoff(
                b2, p2, timeout_ms=60000).result(timeout=60)
            np.testing.assert_array_equal(got["ids"], want["ids"])
            np.testing.assert_array_equal(got["scores"], want["scores"])
            np.testing.assert_array_equal(got["lengths"],
                                          want["lengths"])
        assert pf_sched.prefills_total == 4
        assert de_sched.handoffs_admitted_total == 4
    finally:
        pf_sched.stop()
        de_sched.stop()


def test_handoff_int8_end_to_end_bounded(gen_model_dir):
    """int8-packed handoffs admit fine; the shipped boot state is
    within the per-row quantization bound of the exact state and the
    decode completes with the right geometry."""
    eng, sched = _engine(gen_model_dir, "int8_ho", max_slots=2)
    try:
        feed = {"h0": np.random.RandomState(2)
                .randn(2, H).astype(np.float32)}
        want = eng.generate(feed, timeout_ms=60000)
        boots, pes = sched.prefill(feed)
        schema = payload_schema(eng.generation_meta)
        blob_q = pack_handoff(boots, pes, schema, "default",
                              quant="int8")
        blob_raw = pack_handoff(boots, pes, schema, "default")
        assert len(blob_q) < len(blob_raw)
        header, b2, p2 = unpack_handoff(blob_q)
        for orig, deq in zip(boots + pes, b2 + p2):
            if np.dtype(orig.dtype).kind == "f":
                n = orig.shape[0]
                sc = (np.abs(np.asarray(orig, np.float32)
                             .reshape(n, -1)).max(axis=1) / 127.0
                      ).reshape((n,) + (1,) * (orig.ndim - 1))
                assert np.all(
                    np.abs(np.asarray(deq, np.float32)
                           - np.asarray(orig, np.float32))
                    <= 0.5 * sc + 1e-6)
            else:
                np.testing.assert_array_equal(orig, deq)
        got = sched.submit_handoff(
            b2, p2, timeout_ms=60000).result(timeout=60)
        assert got["ids"].shape == want["ids"].shape
        assert np.all(got["lengths"] >= 1)
    finally:
        sched.stop()


# --------------------------------------------------------- http replica ----


@pytest.fixture()
def disagg_http_stack(gen_model_dir):
    """Two single-model serving stacks of the SAME artifact: one plays
    the prefill replica, one the decode replica."""
    stacks = []
    for _ in range(2):
        reg = ModelRegistry()
        reg.add("default", model_dir=gen_model_dir,
                policy=BucketPolicy(max_batch_size=8),
                scheduler_kw={"max_slots": 4}, timeout_ms=60000.0)
        srv = make_server(reg)
        srv.serve_background()
        stacks.append((reg, srv, f"http://127.0.0.1:{srv.port}"))
    yield stacks
    for reg, srv, _ in stacks:
        srv.shutdown()
        reg.stop()
        srv.server_close()


def test_http_prefill_admit_bit_identical_and_streams(disagg_http_stack):
    """/prefill returns an opaque octet-stream payload; /admit on a
    sibling replica returns the monolithic /generate result bit-exact,
    buffered AND as the NDJSON stream; healthz exposes the per-phase
    counters (satellite 3)."""
    (_, _, pf_url), (_, _, de_url) = disagg_http_stack
    h0 = np.random.RandomState(7).randn(3, H).astype(np.float32)
    with _post(de_url + "/generate",
               {"inputs": {"h0": h0.tolist()},
                "timeout_ms": 60000}) as r:
        want = json.load(r)["outputs"]
    with _post(pf_url + "/prefill/default",
               {"inputs": {"h0": h0.tolist()}}) as r:
        assert r.headers["Content-Type"] == "application/octet-stream"
        assert r.headers[REQUEST_ID_HEADER]
        payload = r.read()
    octet = {"Content-Type": "application/octet-stream"}
    with _post(de_url + "/admit/default", payload, headers=octet) as r:
        got = json.load(r)["outputs"]
    np.testing.assert_array_equal(np.asarray(got["ids"]),
                                  np.asarray(want["ids"]))
    np.testing.assert_array_equal(
        np.asarray(got["scores"], np.float32),
        np.asarray(want["scores"], np.float32))
    # streamed admission: same payload, token events then the terminal
    # done with the same bit-exact outputs
    with _post(de_url + "/admit/default?stream=1&timeout_ms=60000",
               payload, headers=octet) as r:
        assert "ndjson" in r.headers["Content-Type"]
        events = [json.loads(line) for line in r if line.strip()]
    kinds = [e["event"] for e in events]
    assert kinds[-1] == "done" and kinds.count("token") >= 2
    np.testing.assert_array_equal(
        np.asarray(events[-1]["outputs"]["ids"]),
        np.asarray(want["ids"]))
    with urllib.request.urlopen(pf_url + "/healthz", timeout=30) as r:
        load = json.load(r)["load"]
    assert load["prefills_total"] == 1
    assert load["handoffs_admitted_total"] == 0
    with urllib.request.urlopen(de_url + "/healthz", timeout=30) as r:
        load = json.load(r)["load"]
    assert load["handoffs_admitted_total"] == 2
    assert load["free_slots"] == load["max_slots"] \
        - load["active_slots"]


def test_http_admit_schema_mismatch_is_409(disagg_http_stack):
    """A payload whose schema identity disagrees with the admitting
    artifact → 409 with kind=HandoffSchemaError and the rollout fix in
    the message (NOT a retryable 503: a same-version sibling would
    reject it identically). Garbage bytes → 400."""
    (_, _, pf_url), (_, _, de_url) = disagg_http_stack
    h0 = np.zeros((1, H), np.float32)
    with _post(pf_url + "/prefill", {"inputs": {"h0": h0.tolist()}}) \
            as r:
        payload = r.read()
    # tamper the header's state fingerprint, keeping the layout valid
    (hlen,) = struct.unpack_from(">I", payload, 5)
    hdr = json.loads(payload[9:9 + hlen].decode())
    hdr["state_fingerprint"] = "deadbeef00000000"
    new_hdr = json.dumps(hdr, sort_keys=True,
                         separators=(",", ":")).encode()
    bad = (payload[:5] + struct.pack(">I", len(new_hdr)) + new_hdr
           + payload[9 + hlen:])
    octet = {"Content-Type": "application/octet-stream"}
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(de_url + "/admit", bad, headers=octet)
    assert ei.value.code == 409
    err = json.load(ei.value)
    assert err["kind"] == "HandoffSchemaError"
    assert "fleetctl rollout" in err["error"]
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(de_url + "/admit", b"garbage bytes", headers=octet)
    assert ei.value.code == 400


# ------------------------------------------------------ router: phases -----


def test_replica_phase_validation_scoring_and_pick():
    """Per-class JSQ: a prefill replica scores on queue depth +
    compute backlog (queue age; its decode pool never fills), a decode
    replica on how few FREE slots remain; pick(phase=...) only
    considers that class and monolithic (phase=None) replicas keep the
    original formula."""
    r = Router()
    with pytest.raises(ValueError, match="phase"):
        r.add_replica("http://127.0.0.1:9001", phase="encode")
    pf = r.add_replica("http://127.0.0.1:9001", name="pf",
                       phase="prefill")
    de = r.add_replica("http://127.0.0.1:9002", name="de",
                       phase="decode")
    mono = r.add_replica("http://127.0.0.1:9003", name="mono")
    for x in (pf, de, mono):
        x.up = True
    pf.snapshot = {"queue_depth": 2, "queue_age_ms": 1000.0,
                   "active_slots": 3, "max_slots": 4}
    assert pf.score() == pytest.approx(2 + 1.0)  # slots ignored
    de.snapshot = {"queue_depth": 0, "active_slots": 1, "max_slots": 4}
    assert de.score() == pytest.approx(-3.0)  # minus free slots
    mono.snapshot = {"queue_depth": 1, "active_slots": 2}
    assert mono.score() == pytest.approx(3.0)
    assert r.pick(phase="prefill").name == "pf"
    assert r.pick(phase="decode").name == "de"
    assert r.pick().name == "de"  # monolithic pick sees every replica
    # a decode replica with MORE free slots wins the decode pick
    de2 = r.add_replica("http://127.0.0.1:9004", name="de2",
                        phase="decode")
    de2.up = True
    de2.snapshot = {"queue_depth": 0, "active_slots": 0,
                    "max_slots": 4}
    assert r.pick(phase="decode").name == "de2"
    # an exhausted class picks NOTHING — it never spills into the
    # other class or the monolithic pool (dispatch turns this into
    # the retryable NoReplicaError)
    assert r.pick(exclude=("pf",), phase="prefill") is None
    r.close()


def test_router_phase_metric_families():
    """Satellite 3: the unified /metrics surface grows per-PHASE
    aggregate gauges (new pt_phase_* families — the per-replica series
    keep their labels)."""
    reg = obs_metrics.MetricsRegistry()
    router = Router(registry=reg)
    pf_sim, de_sim = SimReplica(slots=4), SimReplica(slots=4)
    try:
        pf = router.add_replica(pf_sim.url, name="pf", phase="prefill")
        de = router.add_replica(de_sim.url, name="de", phase="decode")
        assert router.probe_one(pf) and router.probe_one(de)
        fams = promparse.parse_text(reg.render())
        for fam in ("pt_phase_replicas", "pt_phase_queue_depth",
                    "pt_phase_inflight", "pt_phase_free_slots"):
            phases = {s[1]["phase"] for s in fams[fam].samples}
            assert phases == {"prefill", "decode"}, fam
        reps = {s[1]["phase"]: s[2]
                for s in fams["pt_phase_replicas"].samples}
        assert reps == {"prefill": 1.0, "decode": 1.0}
        free = {s[1]["phase"]: s[2]
                for s in fams["pt_phase_free_slots"].samples}
        assert free["decode"] == 4.0
    finally:
        router.close()
        pf_sim.kill()
        de_sim.kill()


# ------------------------------------------- dispatcher over sim fleets ----


def _phased_sims(n_prefill=1, n_decode=1, fingerprint="fp-v1",
                 registry=None, **sim_kw):
    reg = registry or obs_metrics.MetricsRegistry()
    router = Router(probe_interval_s=0.05, registry=reg).start()
    pf_sims = [SimReplica(fingerprint=fingerprint, **sim_kw)
               for _ in range(n_prefill)]
    de_sims = [SimReplica(fingerprint=fingerprint, **sim_kw)
               for _ in range(n_decode)]
    for i, s in enumerate(pf_sims):
        router.add_replica(s.url, name=f"pf{i}", phase="prefill")
    for i, s in enumerate(de_sims):
        router.add_replica(s.url, name=f"de{i}", phase="decode")
    _wait_until(lambda: all(r.up for r in router.replicas()),
                msg="sim replicas up")
    return reg, router, pf_sims, de_sims


def test_dispatcher_splits_generate_across_phases():
    """/generate through a disagg RouterServer: prefill runs on the
    prefill sim, the payload ships, decode admits — buffered and
    streamed — and the transfer metrics land on the router registry."""
    reg, router, (pf_sim,), (de_sim,) = _phased_sims()
    server = make_router_server(router,
                                disagg=DisaggDispatcher(router))
    server.serve_background()
    url = f"http://127.0.0.1:{server.port}"
    try:
        with _post(url + "/generate",
                   {"sim_prefill_ms": 5, "sim_decode_ms": 5,
                    "tokens": 3}) as r:
            assert r.status == 200
            out = json.load(r)
        assert out["outputs"]["ids"] == [[3]]
        assert pf_sim.prefills_total == 1
        assert de_sim.handoffs_admitted_total == 1
        with _post(url + "/generate",
                   {"stream": True, "tokens": 4, "sim_decode_ms": 20,
                    "timeout_ms": 30000}) as r:
            assert "ndjson" in r.headers["Content-Type"]
            events = [json.loads(line) for line in r if line.strip()]
        kinds = [e["event"] for e in events]
        assert kinds.count("token") == 4 and kinds[-1] == "done"
        assert de_sim.handoffs_admitted_total == 2
        render = reg.render()
        fams = promparse.parse_text(render)
        assert fams["pt_handoff_total"].samples[0][2] == 2.0
        assert fams["pt_handoff_bytes_total"].samples[0][2] > 0
        assert "pt_handoff_seconds_bucket" in render
        assert fams["pt_disagg_reprefills_total"].samples[0][2] == 0.0
    finally:
        server.shutdown()
        server.server_close()
        router.close()
        pf_sim.kill()
        de_sim.kill()


def test_decode_failover_reships_same_payload():
    """Single-replica decode death is absorbed by the router's normal
    dispatch failover: the SAME payload lands on the next-best decode
    replica, no re-prefill spent."""
    reg, router, (pf_sim,), (de0, de1) = _phased_sims(n_decode=2)
    server = make_router_server(router,
                                disagg=DisaggDispatcher(router))
    server.serve_background()
    url = f"http://127.0.0.1:{server.port}"
    try:
        de0.kill()  # connection refused → failover inside dispatch
        with _post(url + "/generate", {"tokens": 2}) as r:
            assert r.status == 200
        assert pf_sim.prefills_total == 1  # prefill ran ONCE
        assert de1.handoffs_admitted_total == 1
        fams = promparse.parse_text(reg.render())
        assert fams["pt_disagg_reprefills_total"].samples[0][2] == 0.0
    finally:
        server.shutdown()
        server.server_close()
        router.close()
        pf_sim.kill()
        de1.kill()


def test_decode_class_death_reprefills_then_retryable_503():
    """Class-wide decode refusal: ONE re-prefill on a DIFFERENT
    prefill replica, then a retryable 503 (Retry-After). Registering a
    fresh decode replica afterwards recovers without operator help."""
    reg, router, (pf0, pf1), (de0,) = _phased_sims(n_prefill=2)
    server = make_router_server(router,
                                disagg=DisaggDispatcher(router))
    server.serve_background()
    url = f"http://127.0.0.1:{server.port}"
    try:
        de0.kill()
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url + "/generate", {"tokens": 2}, timeout=30)
        assert ei.value.code == 503
        assert ei.value.headers["Retry-After"]
        fams = promparse.parse_text(reg.render())
        assert fams["pt_disagg_reprefills_total"].samples[0][2] == 1.0
        # the re-prefill went to the OTHER prefill replica
        assert pf0.prefills_total + pf1.prefills_total == 2
        assert {pf0.prefills_total, pf1.prefills_total} == {1}
        # recovery: a fresh decode replica joins, traffic flows again
        de1 = SimReplica(fingerprint="fp-v1")
        r_new = router.add_replica(de1.url, name="de1", phase="decode")
        _wait_until(lambda: r_new.up, msg="replacement decode up")
        try:
            with _post(url + "/generate", {"tokens": 2}) as r:
                assert r.status == 200
            assert de1.handoffs_admitted_total == 1
        finally:
            de1.kill()
    finally:
        server.shutdown()
        server.server_close()
        router.close()
        pf0.kill()
        pf1.kill()
        de0.kill()


def test_schema_mismatch_is_not_retried_across_siblings():
    """A 409 from /admit is relayed to the client verbatim — the
    dispatcher must NOT burn a re-prefill or try a same-version
    sibling (it would reject identically; the fix is a rollout)."""
    reg = obs_metrics.MetricsRegistry()
    router = Router(probe_interval_s=0.05, registry=reg).start()
    pf_sim = SimReplica(fingerprint="fp-A")
    de_sims = [SimReplica(fingerprint="fp-B") for _ in range(2)]
    router.add_replica(pf_sim.url, name="pf0", phase="prefill")
    for i, s in enumerate(de_sims):
        router.add_replica(s.url, name=f"de{i}", phase="decode")
    _wait_until(lambda: all(r.up for r in router.replicas()),
                msg="sims up")
    server = make_router_server(router,
                                disagg=DisaggDispatcher(router))
    server.serve_background()
    url = f"http://127.0.0.1:{server.port}"
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url + "/generate", {"tokens": 1}, timeout=30)
        assert ei.value.code == 409
        assert json.load(ei.value)["kind"] == "HandoffSchemaError"
        assert sum(s.handoffs_admitted_total for s in de_sims) == 0
        fams = promparse.parse_text(reg.render())
        assert fams["pt_disagg_reprefills_total"].samples[0][2] == 0.0
    finally:
        server.shutdown()
        server.server_close()
        router.close()
        pf_sim.kill()
        for s in de_sims:
            s.kill()


def test_dispatcher_rejects_unknown_quant():
    with pytest.raises(ValueError, match="quant"):
        DisaggDispatcher(Router(), quant="fp4")


# -------------------------------------------------- fleet: two classes -----


def _sim_spawner(**kw):
    def spawn():
        return SimReplica(**kw)
    return spawn


def test_disagg_fleet_deficit_promotion_replaces_dead_prefill():
    """One warm pool, two classes: when the prefill member dies, the
    supervisor's phase-agnostic replacement lands in the PREFILL class
    because that's the class below target (deficit assignment)."""
    reg = obs_metrics.MetricsRegistry()
    router = Router(probe_interval_s=0.05, registry=reg)
    fleet = DisaggFleet(_sim_spawner(), prefill_replicas=1,
                        decode_replicas=1, standby=1, router=router,
                        supervise_interval_s=0.1, ready_timeout_s=10.0)
    fleet.start()
    try:
        _wait_until(lambda: fleet.phase_counts()
                    == {"prefill": 1, "decode": 1},
                    msg="both classes populated")
        d = fleet.describe()
        assert d["phases"]["prefill"]["target"] == 1
        assert d["phases"]["decode"]["target"] == 1
        pf_name = next(r.name for r in router.replicas()
                       if r.phase == "prefill")
        fleet._procs[pf_name].kill()
        _wait_until(lambda: pf_name not in fleet._procs
                    and fleet.phase_counts()
                    == {"prefill": 1, "decode": 1},
                    timeout=15, msg="prefill replacement")
        new_pf = [r for r in router.replicas()
                  if r.phase == "prefill" and not r.draining]
        assert len(new_pf) == 1 and new_pf[0].name != pf_name
    finally:
        fleet.stop()


def test_disagg_fleet_targeted_scale_and_per_class_floor():
    reg = obs_metrics.MetricsRegistry()
    router = Router(probe_interval_s=0.05, registry=reg)
    fleet = DisaggFleet(_sim_spawner(), prefill_replicas=1,
                        decode_replicas=1, standby=1, router=router,
                        supervise_interval_s=0.2, ready_timeout_s=10.0)
    fleet.start()
    try:
        _wait_until(lambda: fleet.phase_counts()
                    == {"prefill": 1, "decode": 1}, msg="fleet up")
        with pytest.raises(ValueError, match="phase"):
            PhaseFleet(fleet, "encode")
        pf_view = PhaseFleet(fleet, "prefill")
        assert pf_view.size() == 1
        # targeted scale-up promotes a standby INTO the class and
        # bumps its target
        names = []
        _wait_until(lambda: bool(
            names.extend(fleet.scale_up(1, phase="prefill")) or names),
            msg="standby promoted")
        assert fleet.targets["prefill"] == 2
        assert pf_view.size() == 2
        promoted = [r for r in router.replicas() if r.name in names]
        assert promoted and promoted[0].phase == "prefill"
        # the phase view only sees its class
        assert {r.phase for r in pf_view.router.replicas()} \
            == {"prefill"}
        # scale-down retires back to one; the last member of a class
        # is never retired
        victims = fleet.scale_down(1, drain_timeout_s=5.0,
                                   phase="prefill")
        assert len(victims) == 1
        _wait_until(lambda: pf_view.size() == 1, msg="retired")
        assert fleet.scale_down(1, phase="prefill") == []
        assert fleet.scale_down(1, phase="decode") == []
        assert fleet.targets["prefill"] == 1
    finally:
        fleet.stop()


def test_phase_autoscalers_distinct_metric_families():
    """Satellite: the two per-class control loops are stock
    Autoscalers under distinct metric families — both render on ONE
    registry without colliding, and the default family is unchanged
    for monolithic fleets."""
    import inspect

    assert inspect.signature(Autoscaler.__init__) \
        .parameters["family"].default == "pt_autoscale"
    reg = obs_metrics.MetricsRegistry()
    router = Router(probe_interval_s=0.05, registry=reg)
    fleet = DisaggFleet(_sim_spawner(), prefill_replicas=1,
                        decode_replicas=1, router=router,
                        supervise_interval_s=0.2, ready_timeout_s=10.0)
    fleet.start()
    try:
        pair = make_phase_autoscalers(fleet)
        res = pair.tick()
        assert set(res) == {"prefill", "decode"}
        st = pair.stats()
        assert st["prefill"] != st["decode"]
        render = reg.render()
        assert "pt_autoscale_prefill_replicas" in render
        assert "pt_autoscale_decode_replicas" in render
        # the prefill loop's occupancy signal is disabled (a prefill
        # replica's decode pool is always empty)
        assert pair.prefill.cfg.up_occupancy > 1.0
        assert pair.decode.cfg.up_occupancy <= 1.0
    finally:
        fleet.stop()


# ------------------------------------------------------- trace mix ---------


def test_trace_disagg_mix_is_digest_stable():
    """Satellite 2: the disagg fields follow the guarded-draw
    contract — fraction=0 specs consume NO randomness (pre-disagg
    traces replay byte-identically), fraction>0 marks events with a
    bounded lognormal prefill cost + short decode budget,
    deterministically."""
    base = TraceSpec(duration_s=10.0, seed=7)
    explicit = TraceSpec(duration_s=10.0, seed=7, disagg_fraction=0.0)
    assert trace_digest(generate_trace(base)) \
        == trace_digest(generate_trace(explicit))
    spec = TraceSpec(duration_s=10.0, seed=7, disagg_fraction=0.6)
    t1, t2 = generate_trace(spec), generate_trace(spec)
    assert trace_digest(t1) == trace_digest(t2)
    assert trace_digest(t1) != trace_digest(generate_trace(base))
    marked = [e for e in t1 if "prefill_ms" in e]
    assert marked
    for e in marked:
        assert 0.0 < e["prefill_ms"] <= spec.max_prefill_ms
        assert (spec.decode_tokens_min <= e["decode_tokens"]
                <= spec.decode_tokens_max)
    frac = len(marked) / len(t1)
    assert 0.4 < frac < 0.8
    d = spec.describe()
    assert d["disagg_fraction"] == 0.6
    assert json.loads(json.dumps(d)) == d
    with pytest.raises(ValueError):
        TraceSpec(disagg_fraction=1.5)
    with pytest.raises(ValueError):
        TraceSpec(decode_tokens_min=0)
    with pytest.raises(ValueError):
        TraceSpec(decode_tokens_min=9, decode_tokens_max=3)


# ------------------------------------------------------------ lint ---------

# blocking network/clock calls banned from the phase-pick path — the
# same contract (and call list) as test_router's Router.pick lint,
# minus Router.dispatch itself, which OWNS every round-trip, and
# minus "join" (the query string is str.join-ed; thread joins are
# caught by "wait")
_BLOCKING_CALLS = {
    "urlopen", "request", "getresponse", "read", "readline", "recv",
    "send", "sendall", "connect", "sleep", "wait", "select",
    "accept", "probe_one", "_attempt",
}
_BLOCKING_NAMES = {"HTTPConnection", "urlopen", "socket",
                   "create_connection"}

# host-sync calls banned from the admission hot path: the ONE d2h
# fence lives in prefill's gather_handoff_rows; admission is
# device_put + the jitted pool_admit, never a host round-trip
_HOST_SYNC_CALLS = {"device_get", "block_until_ready", "tolist",
                    "item", "copy_to_host_async"}


def _find_method(tree, cls, name):
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef) and node.name == cls:
            for item in node.body:
                if isinstance(item, ast.FunctionDef) and \
                        item.name == name:
                    return item
    return None


def _find_function(tree, name):
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef) and node.name == name:
            return node
    return None


def _called_names(fn):
    for node in ast.walk(fn):
        if isinstance(node, ast.Call):
            f = node.func
            yield (f.attr if isinstance(f, ast.Attribute)
                   else f.id if isinstance(f, ast.Name) else None)


def test_dispatcher_generate_has_no_direct_io():
    """AST lint (satellite 5): DisaggDispatcher.generate performs NO
    blocking I/O itself — every network round-trip goes through
    Router.dispatch, so phase-picking inherits the pick path's
    latency guarantees."""
    import paddle_tpu.serving.disagg.dispatch as mod

    with open(mod.__file__) as f:
        tree = ast.parse(f.read())
    fn = _find_method(tree, "DisaggDispatcher", "generate")
    assert fn is not None, "DisaggDispatcher.generate not found"
    for called in _called_names(fn):
        assert called not in _BLOCKING_CALLS, (
            f"DisaggDispatcher.generate calls blocking {called!r} "
            "outside Router.dispatch")
        assert called not in _BLOCKING_NAMES, (
            f"DisaggDispatcher.generate constructs {called!r}")


def test_handoff_admit_hot_path_has_no_host_sync():
    """AST lint (satellite 5): submit_handoff and the restore helper
    never host-sync — shipped state is device_put straight into the
    pool-admit path; the only d2h fence of the whole handoff is
    prefill's gather_handoff_rows."""
    import paddle_tpu.pipeline.elastic as elastic_mod
    import paddle_tpu.serving.scheduler as sched_mod

    with open(sched_mod.__file__) as f:
        sched_tree = ast.parse(f.read())
    with open(elastic_mod.__file__) as f:
        elastic_tree = ast.parse(f.read())
    targets = [
        ("ContinuousScheduler.submit_handoff",
         _find_method(sched_tree, "ContinuousScheduler",
                      "submit_handoff")),
        ("elastic.restore_handoff_rows",
         _find_function(elastic_tree, "restore_handoff_rows")),
    ]
    for label, fn in targets:
        assert fn is not None, f"{label} not found (lint is stale)"
        for called in _called_names(fn):
            assert called not in _HOST_SYNC_CALLS, (
                f"{label} calls host-syncing {called!r} in the "
                "handoff admission hot path")
            assert called not in _BLOCKING_CALLS or called == "read", (
                f"{label} calls blocking {called!r}")


def test_handoff_wire_module_imports_no_jax():
    """The serialize side of the hot path is pure host numpy: the wire
    module never imports jax at the top level (pack/unpack must not
    drag device state or tracing into byte shuffling)."""
    import paddle_tpu.serving.disagg.handoff as mod

    with open(mod.__file__) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                assert not alias.name.split(".")[0] == "jax"
        elif isinstance(node, ast.ImportFrom):
            mod_name = (node.module or "").split(".")[0]
            assert mod_name != "jax"


# ----------------------------------------------------------- perfetto ------


def test_perfetto_capture_links_phases_by_request_id(gen_model_dir):
    """Satellite 3: ONE armed capture over the full in-process
    topology (prefill replica, router+dispatcher, decode replica)
    shows the prefill → transfer → decode span chain, every span
    carrying the same X-PT-Request-Id."""
    stacks = []
    for _ in range(2):
        reg = ModelRegistry()
        reg.add("default", model_dir=gen_model_dir,
                policy=BucketPolicy(max_batch_size=8),
                scheduler_kw={"max_slots": 2}, timeout_ms=60000.0)
        srv = make_server(reg)
        srv.serve_background()
        stacks.append((reg, srv))
    router = Router(probe_interval_s=0.05).start()
    router.add_replica(f"http://127.0.0.1:{stacks[0][1].port}",
                       name="pf0", phase="prefill")
    router.add_replica(f"http://127.0.0.1:{stacks[1][1].port}",
                       name="de0", phase="decode")
    _wait_until(lambda: all(r.up for r in router.replicas()),
                msg="replicas up")
    server = make_router_server(router,
                                disagg=DisaggDispatcher(router))
    server.serve_background()
    url = f"http://127.0.0.1:{server.port}"
    rid = "disagg-e2e-1"
    try:
        h0 = np.random.RandomState(3).randn(2, H).astype(np.float32)
        with obs_trace.tracing() as tr:
            with _post(url + "/generate",
                       {"inputs": {"h0": h0.tolist()},
                        "timeout_ms": 60000},
                       headers={REQUEST_ID_HEADER: rid}) as r:
                assert r.status == 200
                assert r.headers[REQUEST_ID_HEADER] == rid
                json.load(r)
        doc = tr.to_chrome()
        assert obs_trace.validate_chrome_trace(doc) == []
        spans = [e for e in doc["traceEvents"] if e.get("ph") == "X"]

        def linked(name):
            return [e for e in spans if e["name"] == name
                    and e.get("args", {}).get("request_id") == rid]

        chain = ["http.prefill", "gen.prefill", "disagg.handoff",
                 "http.admit", "gen.admit"]
        got = {name: linked(name) for name in chain}
        for name, evs in got.items():
            assert evs, f"no {name} span linked to {rid}"
        # the phases happen in order: prefill completes before the
        # transfer starts, the transfer starts before decode admission
        pf_end = max(e["ts"] + e["dur"] for e in got["gen.prefill"])
        ho_start = min(e["ts"] for e in got["disagg.handoff"])
        adm_start = min(e["ts"] for e in got["gen.admit"])
        assert pf_end <= ho_start + 1e-3
        assert ho_start <= adm_start + 1e-3
    finally:
        server.shutdown()
        server.server_close()
        router.close()
        for reg, srv in stacks:
            srv.shutdown()
            reg.stop()
            srv.server_close()


# ------------------------------------------------------- fleet e2e ---------


@pytest.mark.fleet
def test_disagg_sim_fleet_e2e_survives_decode_churn():
    """Fleet e2e under the fleet budget: a DisaggFleet of sims behind
    the disagg RouterServer serves a request mix while a decode
    replica dies mid-run — clients only ever see successes or
    retryable 503s, the supervisor restores the class, and the
    phase counters reconcile."""
    reg = obs_metrics.MetricsRegistry()
    router = Router(probe_interval_s=0.05, registry=reg)
    fleet = DisaggFleet(_sim_spawner(slots=4), prefill_replicas=1,
                        decode_replicas=2, standby=1, router=router,
                        supervise_interval_s=0.1, ready_timeout_s=10.0)
    fleet.start()
    server = make_router_server(
        router, fleet=fleet, disagg=DisaggDispatcher(router))
    server.serve_background()
    url = f"http://127.0.0.1:{server.port}"
    ok, retryable = 0, 0
    try:
        _wait_until(lambda: fleet.phase_counts()
                    == {"prefill": 1, "decode": 2}, msg="fleet up")
        for i in range(12):
            if i == 5:  # kill one decode replica mid-run
                de_name = next(r.name for r in router.replicas()
                               if r.phase == "decode"
                               and r.name in fleet._procs)
                fleet._procs[de_name].kill()
            try:
                with _post(url + "/generate",
                           {"tokens": 2, "sim_prefill_ms": 2,
                            "sim_decode_ms": 2}, timeout=30) as r:
                    assert r.status == 200
                    ok += 1
            except urllib.error.HTTPError as e:
                assert e.code == 503, "only retryable errors allowed"
                retryable += 1
        assert ok >= 8
        _wait_until(lambda: fleet.phase_counts()
                    == {"prefill": 1, "decode": 2}, timeout=15,
                    msg="decode class restored")
        # the router counted exactly one admitted handoff per client
        # success (the dead sim took its tally with it, so count at
        # the dispatcher)
        fams = promparse.parse_text(reg.render())
        assert fams["pt_handoff_total"].samples[0][2] == float(ok)
        # /admin/fleet surfaces the per-phase topology
        with urllib.request.urlopen(url + "/admin/fleet",
                                    timeout=10) as r:
            admin = json.load(r)
        assert set(admin["fleet"]["phases"]) == {"prefill", "decode"}
    finally:
        server.shutdown()
        server.server_close()
        fleet.stop()
