"""Tests for the widened layer set (misc transforms + cost family).

Reference analogues: gserver/tests/test_LayerGrad.cpp cases for each layer
(maxout, prelu, cos_sim, pad, crop, multiplex, bilinear_interp, row_conv,
conv_shift, roi_pool, spp, rank/lambda/huber costs, nce, hsigmoid) — here
checked against NumPy oracles and, where natural, torch (CPU) oracles.
"""

import numpy as np
import pytest

import paddle_tpu as pt


def _run(fetch, feed):
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    return exe.run(feed=feed, fetch_list=fetch)


def test_gather_scatter_one_hot():
    x = pt.layers.data("x", shape=[4])
    idx = pt.layers.data("idx", shape=[], dtype=np.int32, append_batch_size=False)
    g = pt.layers.gather(x, idx)
    xv = np.arange(20, dtype=np.float32).reshape(5, 4)
    iv = np.array([3, 0, 3], np.int32)
    (out,) = _run([g], {"x": xv, "idx": iv})
    np.testing.assert_allclose(out, xv[iv])

    pt.reset()
    x = pt.layers.data("x", shape=[4])
    idx = pt.layers.data("idx", shape=[], dtype=np.int32, append_batch_size=False)
    upd = pt.layers.data("upd", shape=[4])
    s = pt.layers.scatter(x, idx, upd, overwrite=False)
    uv = np.ones((2, 4), np.float32)
    iv2 = np.array([1, 1], np.int32)
    (out,) = _run([s], {"x": xv, "idx": iv2, "upd": uv})
    exp = xv.copy()
    exp[1] += 2.0
    np.testing.assert_allclose(out, exp)

    pt.reset()
    lbl = pt.layers.data("l", shape=[1], dtype=np.int32)
    oh = pt.layers.one_hot(lbl, depth=6)
    (out,) = _run([oh], {"l": np.array([[2], [5]], np.int32)})
    assert out.shape == (2, 6) and out[0, 2] == 1 and out[1, 5] == 1


def test_pad_crop_multiplex():
    x = pt.layers.data("x", shape=[2, 3], append_batch_size=False)
    p = pt.layers.pad(x, paddings=[0, 0, 1, 1], pad_value=9.0)
    c = pt.layers.crop(x, offsets=[0, 1], shape=[2, 2])
    xv = np.arange(6, dtype=np.float32).reshape(2, 3)
    pv, cv = _run([p, c], {"x": xv})
    assert pv.shape == (2, 5) and pv[0, 0] == 9.0
    np.testing.assert_allclose(cv, xv[:, 1:3])

    pt.reset()
    a = pt.layers.data("a", shape=[3])
    b = pt.layers.data("b", shape=[3])
    ids = pt.layers.data("ids", shape=[1], dtype=np.int32)
    m = pt.layers.multiplex([a, b], ids)
    av = np.zeros((2, 3), np.float32)
    bv = np.ones((2, 3), np.float32)
    (out,) = _run([m], {"a": av, "b": bv, "ids": np.array([[1], [0]], np.int32)})
    np.testing.assert_allclose(out, [[1, 1, 1], [0, 0, 0]])


def test_maxout_prelu():
    x = pt.layers.data("x", shape=[4, 2, 2])
    y = pt.layers.maxout(x, groups=2)
    rng = np.random.RandomState(0)
    xv = rng.randn(3, 4, 2, 2).astype(np.float32)
    (out,) = _run([y], {"x": xv})
    np.testing.assert_allclose(out, xv.reshape(3, 2, 2, 2, 2).max(axis=2),
                               rtol=1e-6)

    pt.reset()
    x = pt.layers.data("x", shape=[5])
    y = pt.layers.prelu(x, mode="all")
    xv = np.array([[-2.0, -1.0, 0.0, 1.0, 2.0]], np.float32)
    (out,) = _run([y], {"x": xv})
    np.testing.assert_allclose(out, np.where(xv > 0, xv, 0.25 * xv), rtol=1e-6)


def test_similarity_family():
    rng = np.random.RandomState(1)
    xv = rng.randn(4, 6).astype(np.float32)
    yv = rng.randn(4, 6).astype(np.float32)
    x = pt.layers.data("x", shape=[6])
    y = pt.layers.data("y", shape=[6])
    cs = pt.layers.cos_sim(x, y)
    dp = pt.layers.dot_prod(x, y)
    l2 = pt.layers.l2_distance(x, y)
    rn = pt.layers.row_l2_norm(x)
    csv, dpv, l2v, rnv = _run([cs, dp, l2, rn], {"x": xv, "y": yv})
    exp_cs = (xv * yv).sum(1) / (
        np.linalg.norm(xv, axis=1) * np.linalg.norm(yv, axis=1)
    )
    np.testing.assert_allclose(csv[:, 0], exp_cs, rtol=1e-5)
    np.testing.assert_allclose(dpv[:, 0], (xv * yv).sum(1), rtol=1e-5)
    np.testing.assert_allclose(l2v[:, 0], np.linalg.norm(xv - yv, axis=1),
                               rtol=1e-5)
    np.testing.assert_allclose(
        rnv, xv / np.linalg.norm(xv, axis=1, keepdims=True), rtol=1e-5
    )


def test_row_scalar_family():
    rng = np.random.RandomState(2)
    xv = np.abs(rng.randn(3, 4)).astype(np.float32) + 0.5
    yv = rng.randn(3, 4).astype(np.float32)
    wv = rng.rand(3, 1).astype(np.float32)
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[4])
    w = pt.layers.data("w", shape=[1])
    interp = pt.layers.interpolation(x, y, w)
    pw = pt.layers.power(x, w)
    sc = pt.layers.scaling(x, w)
    si = pt.layers.slope_intercept(x, slope=2.0, intercept=-1.0)
    s1 = pt.layers.sum_to_one_norm(x)
    iv, pv, scv, siv, s1v = _run([interp, pw, sc, si, s1],
                                 {"x": xv, "y": yv, "w": wv})
    np.testing.assert_allclose(iv, wv * xv + (1 - wv) * yv, rtol=1e-5)
    np.testing.assert_allclose(pv, np.power(xv, wv), rtol=1e-4)
    np.testing.assert_allclose(scv, wv * xv, rtol=1e-5)
    np.testing.assert_allclose(siv, 2 * xv - 1, rtol=1e-5)
    np.testing.assert_allclose(s1v, xv / xv.sum(1, keepdims=True), rtol=1e-5)


def test_geometry_transforms():
    xv = np.arange(24, dtype=np.float32).reshape(1, 2, 3, 4)
    x = pt.layers.data("x", shape=[2, 3, 4])
    rot = pt.layers.rotate(x)
    sw = pt.layers.switch_order(x)
    rv, sv = _run([rot, sw], {"x": xv})
    np.testing.assert_allclose(rv, np.rot90(xv, k=1, axes=(2, 3)))
    np.testing.assert_allclose(sv, xv.transpose(0, 2, 3, 1))


def test_bilinear_interp_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(3)
    xv = rng.randn(2, 3, 5, 7).astype(np.float32)
    x = pt.layers.data("x", shape=[3, 5, 7])
    y = pt.layers.bilinear_interp(x, out_h=10, out_w=14)
    (out,) = _run([y], {"x": xv})
    ref = torch.nn.functional.interpolate(
        torch.tensor(xv), size=(10, 14), mode="bilinear", align_corners=True
    ).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_im2sequence_row_conv_conv_shift():
    rng = np.random.RandomState(4)
    xv = rng.randn(2, 3, 4, 4).astype(np.float32)
    x = pt.layers.data("x", shape=[3, 4, 4])
    seq = pt.layers.im2sequence(x, block_y=2, block_x=2, stride_y=2, stride_x=2)
    (out,) = _run([seq], {"x": xv})
    assert out.shape == (2, 4, 12)
    # first patch of first image = channels-major 2x2 block
    blk = xv[0, :, 0:2, 0:2].reshape(-1)
    np.testing.assert_allclose(out[0, 0], blk, rtol=1e-6)

    pt.reset()
    tv = rng.randn(2, 5, 3).astype(np.float32)
    t = pt.layers.data("t", shape=[5, 3], append_batch_size=True)
    rc = pt.layers.row_conv(t, future_context_size=2)
    (out,) = _run([rc], {"t": tv})
    assert out.shape == tv.shape

    pt.reset()
    xv2 = rng.randn(3, 8).astype(np.float32)
    yv2 = rng.randn(3, 3).astype(np.float32)
    a = pt.layers.data("a", shape=[8])
    b = pt.layers.data("b", shape=[3])
    csh = pt.layers.conv_shift(a, b)
    (out,) = _run([csh], {"a": xv2, "b": yv2})
    exp = np.zeros_like(xv2)
    for n in range(3):
        for d in range(8):
            for j in range(3):
                exp[n, d] += yv2[n, j] * xv2[n, (d + j - 1) % 8]
    np.testing.assert_allclose(out, exp, rtol=1e-5)


def test_factored_layers():
    rng = np.random.RandomState(5)
    xv = rng.randn(4, 6).astype(np.float32)
    yv = rng.randn(4, 3).astype(np.float32)
    x = pt.layers.data("x", shape=[6])
    y = pt.layers.data("y", shape=[3])
    op = pt.layers.out_prod(x, y)
    fm = pt.layers.factorization_machine(x, factor_size=4)
    bt = pt.layers.bilinear_tensor_product(x, y, size=2)
    sf = pt.layers.selective_fc(x, size=5)
    opv, fmv, btv, sfv = _run([op, fm, bt, sf], {"x": xv, "y": yv})
    np.testing.assert_allclose(
        opv, (xv[:, :, None] * yv[:, None, :]).reshape(4, -1), rtol=1e-5
    )
    assert fmv.shape == (4, 1) and btv.shape == (4, 2) and sfv.shape == (4, 5)


def test_3d_conv_pool_matches_torch():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(6)
    xv = rng.randn(2, 3, 5, 6, 7).astype(np.float32)
    x = pt.layers.data("x", shape=[3, 5, 6, 7])
    y = pt.layers.conv3d(x, num_filters=4, filter_size=3, padding=1,
                         bias_attr=False)
    p = pt.layers.pool3d(x, pool_size=2, pool_type="max")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    w = pt.global_scope().get(
        [v for v in pt.default_main_program().global_block().vars
         if ".w" in v][0]
    )
    yv, pv = exe.run(feed={"x": xv}, fetch_list=[y, p])
    ref = torch.nn.functional.conv3d(
        torch.tensor(xv), torch.tensor(np.asarray(w)), padding=1
    ).numpy()
    np.testing.assert_allclose(yv, ref, rtol=1e-3, atol=1e-4)
    refp = torch.nn.functional.max_pool3d(torch.tensor(xv), 2).numpy()
    np.testing.assert_allclose(pv, refp, rtol=1e-6)


def test_roi_pool_and_spp():
    xv = np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8)
    x = pt.layers.data("x", shape=[1, 8, 8])
    rois = pt.layers.data("rois", shape=[5], append_batch_size=True)
    rp = pt.layers.roi_pool(x, rois, pooled_height=2, pooled_width=2)
    sp = pt.layers.spp(x, pyramid_height=2)
    rv = np.array([[0, 0, 0, 3, 3]], np.float32)  # 4x4 box at origin
    rpv, spv = _run([rp, sp], {"x": xv, "rois": rv})
    # 4x4 box max-pooled 2x2: quadrant maxima
    box = xv[0, 0, 0:4, 0:4]
    exp = np.array([[box[:2, :2].max(), box[:2, 2:].max()],
                    [box[2:, :2].max(), box[2:, 2:].max()]])
    np.testing.assert_allclose(rpv[0, 0], exp)
    assert spv.shape == (1, 1 * (1 + 4))
    assert spv[0, 0] == 63.0  # global max


def test_cost_family_oracles():
    torch = pytest.importorskip("torch")
    rng = np.random.RandomState(7)
    xv = rng.randn(6, 4).astype(np.float32)
    lv = (rng.rand(6, 4) > 0.5).astype(np.float32)
    x = pt.layers.data("x", shape=[4])
    l = pt.layers.data("l", shape=[4])
    bce = pt.layers.sigmoid_cross_entropy_with_logits(x, l)
    (out,) = _run([bce], {"x": xv, "l": lv})
    ref = torch.nn.functional.binary_cross_entropy_with_logits(
        torch.tensor(xv), torch.tensor(lv), reduction="none"
    ).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    pt.reset()
    pv = 1 / (1 + np.exp(-xv))
    x2 = pt.layers.data("x", shape=[4])
    l2 = pt.layers.data("l", shape=[4])
    b2 = pt.layers.binary_cross_entropy(x2, l2)
    (out2,) = _run([b2], {"x": pv, "l": lv})
    ref2 = torch.nn.functional.binary_cross_entropy(
        torch.tensor(pv), torch.tensor(lv), reduction="none"
    ).numpy()
    np.testing.assert_allclose(out2, ref2, rtol=1e-4, atol=1e-5)

    pt.reset()
    sv = rng.randn(5, 3).astype(np.float32)
    tv = rng.randn(5, 3).astype(np.float32)
    a = pt.layers.data("a", shape=[3])
    b = pt.layers.data("b", shape=[3])
    sl1 = pt.layers.smooth_l1(a, b)
    (out3,) = _run([sl1], {"a": sv, "b": tv})
    ref3 = torch.nn.functional.smooth_l1_loss(
        torch.tensor(sv), torch.tensor(tv), reduction="none"
    ).numpy().sum(1, keepdims=True)
    np.testing.assert_allclose(out3, ref3, rtol=1e-4, atol=1e-5)


def test_rank_and_margin_costs():
    rng = np.random.RandomState(8)
    lv = rng.randn(5, 1).astype(np.float32)
    rv = rng.randn(5, 1).astype(np.float32)
    yv = (rng.rand(5, 1) > 0.5).astype(np.float32)
    left = pt.layers.data("left", shape=[1])
    right = pt.layers.data("right", shape=[1])
    label = pt.layers.data("label", shape=[1])
    rc = pt.layers.rank_cost(left, right, label)
    ml = pt.layers.margin_rank_loss(left, right, label, margin=0.1)
    rcv, mlv = _run([rc, ml], {"left": lv, "right": rv, "label": yv})
    o = (lv - rv)[:, 0]
    exp = np.log1p(np.exp(-np.abs(o))) + np.maximum(o, 0) - yv[:, 0] * o
    np.testing.assert_allclose(rcv[:, 0], exp, rtol=1e-5, atol=1e-6)
    expm = np.maximum(0, -yv[:, 0] * o + 0.1)
    np.testing.assert_allclose(mlv[:, 0], expm, rtol=1e-5, atol=1e-6)


def test_huber_classification_and_selfnorm():
    xv = np.array([[-2.0], [-0.5], [0.5], [2.0]], np.float32)
    yv = np.array([[0], [0], [1], [1]], np.float32)
    x = pt.layers.data("x", shape=[1])
    y = pt.layers.data("y", shape=[1])
    hc = pt.layers.huber_classification_cost(x, y)
    (out,) = _run([hc], {"x": xv, "y": yv})
    # y=-1,x=-2 → a=2 → 0 ; y=-1,x=-.5 → a=.5 → (1-.5)^2 ; etc.
    np.testing.assert_allclose(out[:, 0], [0.0, 0.25, 0.25, 0.0], rtol=1e-5)

    pt.reset()
    probs = np.abs(np.random.RandomState(9).randn(4, 5)).astype(np.float32) + 0.1
    lab = np.array([[0], [1], [2], [3]], np.int32)
    p = pt.layers.data("p", shape=[5])
    l = pt.layers.data("l", shape=[1], dtype=np.int32)
    cs = pt.layers.cross_entropy_with_selfnorm(p, l, softmax_selfnorm_alpha=0.5)
    (out2,) = _run([cs], {"p": probs, "l": lab})
    z = probs.sum(1)
    exp = -np.log(probs[np.arange(4), lab[:, 0]] / z) + 0.5 * np.log(z) ** 2
    np.testing.assert_allclose(out2[:, 0], exp, rtol=1e-4)


def test_lambda_cost_ranks_correctly():
    # perfectly-ordered list should have cost ≈ -1 (NDCG=1); inverted worse
    good = np.array([[3.0, 2.0, 1.0, 0.5]], np.float32)
    lab = np.array([[3.0, 2.0, 1.0, 0.0]], np.float32)
    s = pt.layers.data("s", shape=[4])
    l = pt.layers.data("l", shape=[4])
    lc = pt.layers.lambda_cost(s, l, NDCG_num=4)
    (out_good,) = _run([lc], {"s": good, "l": lab})
    pt.reset()
    s = pt.layers.data("s", shape=[4])
    l = pt.layers.data("l", shape=[4])
    lc = pt.layers.lambda_cost(s, l, NDCG_num=4)
    (out_bad,) = _run([lc], {"s": -good, "l": lab})
    assert out_good[0, 0] < out_bad[0, 0]  # lower cost = better ranking
    assert out_good[0, 0] < -0.8


def test_nce_and_hsigmoid_train():
    """Both sampled-softmax surrogates must be trainable: loss decreases."""
    rng = np.random.RandomState(10)
    n, d, c = 32, 8, 17
    xv = rng.randn(n, d).astype(np.float32)
    lv = rng.randint(0, c, (n, 1)).astype(np.int32)

    for kind in ("nce", "hsigmoid"):
        pt.reset()
        x = pt.layers.data("x", shape=[d])
        l = pt.layers.data("l", shape=[1], dtype=np.int32)
        h = pt.layers.fc(x, size=16, act="tanh")
        if kind == "nce":
            cost = pt.layers.nce(h, l, num_classes=c, num_neg_samples=5)
        else:
            cost = pt.layers.hsigmoid(h, l, num_classes=c)
        loss = pt.layers.mean(cost)
        pt.optimizer.SGD(learning_rate=0.2).minimize(loss)
        exe = pt.Executor()
        exe.run(pt.default_startup_program())
        losses = []
        for _ in range(30):
            (lval,) = exe.run(feed={"x": xv, "l": lv}, fetch_list=[loss])
            losses.append(float(lval))
        assert losses[-1] < losses[0] * 0.9, (kind, losses[0], losses[-1])


def test_hsigmoid_path_tables():
    from paddle_tpu.ops.cost_ops import _hsigmoid_tables

    nodes, bits, valid = _hsigmoid_tables(8)
    # class 0: code 8 = 0b1000, depth 3, ancestors 1,2,4 → rows 0,1,3
    np.testing.assert_array_equal(nodes[0][:3], [0, 1, 3])
    np.testing.assert_array_equal(bits[0][:3], [0, 0, 0])
    # class 7: code 15 = 0b1111 → ancestors 1,3,7 → rows 0,2,6, bits 1,1,1
    np.testing.assert_array_equal(nodes[7][:3], [0, 2, 6])
    np.testing.assert_array_equal(bits[7][:3], [1, 1, 1])
    assert valid[0].sum() == 3


def test_sampling_id_distribution():
    probs = np.array([[0.0, 1.0, 0.0], [1.0, 0.0, 0.0]], np.float32)
    x = pt.layers.data("x", shape=[3])
    s = pt.layers.sampling_id(x)
    (out,) = _run([s], {"x": probs})
    np.testing.assert_array_equal(out, [1, 0])


def test_row_conv_lod_respects_boundaries():
    from paddle_tpu.core.lod import LoDArray

    x = pt.layers.data("x", shape=[-1, 2], lod_level=1, append_batch_size=False)
    rc = pt.layers.row_conv(x, future_context_size=1)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    seqs = [[[1.0, 1.0], [2.0, 2.0]], [[10.0, 10.0]]]
    lod = LoDArray.from_sequences([np.asarray(s, np.float32) for s in seqs],
                                  bucket=8)
    wname = [v for v in pt.default_main_program().global_block().vars
             if ".w" in v][0]
    pt.global_scope().set(wname, np.array([[1.0, 1.0], [1.0, 1.0]], np.float32))
    (out,) = exe.run(feed={"x": lod}, fetch_list=[rc], return_numpy=False)
    d = np.asarray(out.data)
    # token 0: x0 + x1 = [3,3]; token 1 (last of seq 0): must NOT see seq 1
    np.testing.assert_allclose(d[0], [3.0, 3.0])
    np.testing.assert_allclose(d[1], [2.0, 2.0])
    np.testing.assert_allclose(d[2], [10.0, 10.0])


def test_roi_pool_empty_bins_are_zero():
    xv = np.arange(64, dtype=np.float32).reshape(1, 1, 8, 8) + 1.0
    x = pt.layers.data("x", shape=[1, 8, 8])
    rois = pt.layers.data("rois", shape=[5], append_batch_size=True)
    rp = pt.layers.roi_pool(x, rois, pooled_height=2, pooled_width=2)
    rv = np.array([[0, 2, 2, 2, 2]], np.float32)  # 1x1 box < 2x2 grid
    (out,) = _run([rp], {"x": xv, "rois": rv})
    assert np.isfinite(out).all()
    # floor/ceil windows: every bin of a 1x1 ROI covers the single pixel
    # (reference hstart=floor(b*1/2)=0, hend=ceil((b+1)*1/2)=1 for both bins)
    np.testing.assert_allclose(out[0, 0], np.full((2, 2), xv[0, 0, 2, 2]))


def test_spp_small_input_no_padding_artifacts():
    # h=w=2 with pyramid_height=3 (finest grid 4x4 > input): every bin must
    # still read a real pixel — no -inf, and avg bins must not be diluted
    xv = np.ones((1, 2, 2, 2), np.float32) * 5.0
    x = pt.layers.data("x", shape=[2, 2, 2])
    sm = pt.layers.spp(x, pyramid_height=3, pool_type="max")
    sa = pt.layers.spp(x, pyramid_height=3, pool_type="avg")
    mv, av = _run([sm, sa], {"x": xv})
    assert mv.shape == (1, 2 * (1 + 4 + 16)) and av.shape == mv.shape
    np.testing.assert_allclose(mv, 5.0)
    np.testing.assert_allclose(av, 5.0)


def test_cos_sim_lod_feeds_sequence_pool():
    from paddle_tpu.core.lod import LoDArray

    x = pt.layers.data("x", shape=[-1, 3], lod_level=1, append_batch_size=False)
    y = pt.layers.data("y", shape=[-1, 3], lod_level=1, append_batch_size=False)
    cs = pt.layers.cos_sim(x, y)
    pooled = pt.layers.sequence_pool(cs, "sum")
    exe = pt.Executor()
    seqs = [[[1.0, 0.0, 0.0], [0.0, 1.0, 0.0]], [[0.0, 0.0, 2.0]]]
    lx = LoDArray.from_sequences([np.asarray(s, np.float32) for s in seqs],
                                 bucket=8)
    (out,) = exe.run(feed={"x": lx, "y": lx}, fetch_list=[pooled])
    np.testing.assert_allclose(out[:2, 0], [2.0, 1.0], rtol=1e-5)


def test_roi_pool_overlapping_bins():
    # ROI height/width 5 with 2x2 grid: reference floor/ceil windows overlap
    # at the middle row/col — row 2 belongs to BOTH bins
    xv = np.zeros((1, 1, 8, 8), np.float32)
    xv[0, 0, 2, 2] = 99.0  # center pixel of a 5x5 box at origin
    x = pt.layers.data("x", shape=[1, 8, 8])
    rois = pt.layers.data("rois", shape=[5], append_batch_size=True)
    rp = pt.layers.roi_pool(x, rois, pooled_height=2, pooled_width=2)
    rv = np.array([[0, 0, 0, 4, 4]], np.float32)
    (out,) = _run([rp], {"x": xv, "rois": rv})
    # pixel (2,2) must appear in every bin's max (reference semantics)
    np.testing.assert_allclose(out[0, 0], [[99, 99], [99, 99]])


def test_reduce_keep_dim_static_shape_and_value():
    """reduce with dim=None keep_dim=True keeps rank (declared == runtime)."""
    x = pt.layers.data("x", shape=[2, 3], append_batch_size=False)
    r = pt.layers.reduce_sum(x, keep_dim=True)
    assert tuple(r.shape) == (1, 1)
    r2 = pt.layers.reduce_sum(x, dim=1, keep_dim=True)
    assert tuple(r2.shape) == (2, 1)
    exe = pt.Executor()
    out, out2 = exe.run(
        feed={"x": np.arange(6, dtype=np.float32).reshape(2, 3)},
        fetch_list=[r, r2])
    assert out.shape == (1, 1) and out[0, 0] == 15
    assert out2.shape == (2, 1)
