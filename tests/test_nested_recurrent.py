"""NestedRecurrentGroup (hierarchical RNN) tests.

Reference analogue: gserver/tests/test_RecurrentGradientMachine.cpp's
sub-sequence configs — the outer recurrence must see exactly one frame per
sub-sequence, in order, with memories carried across frames; verified
against a plain-python loop oracle.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.lod import LoDArray

D, H = 3, 4


def _nested(paragraphs):
    return LoDArray.from_nested_sequences(
        [[np.asarray(s, np.float32) for s in p] for p in paragraphs],
        bucket=64,
    )


def _build(S, L):
    x = pt.layers.data("x", shape=[-1, D], lod_level=2,
                       append_batch_size=False)
    rnn = pt.layers.NestedRecurrentGroup(max_subseqs=S, max_sublen=L)
    with rnn.step():
        sub, sub_mask = rnn.step_input(x)       # [B, L, D], [B, L]
        h_prev = rnn.memory(shape=[H])
        # inner reduction: masked mean over the sub-sequence tokens
        m = pt.layers.cast(sub_mask, np.float32)
        summed = pt.layers.reduce_sum(
            pt.layers.elementwise_mul(sub, m, axis=0), dim=1)
        # clip the count: padded outer steps have 0 tokens and an
        # unguarded 0/0 NaN would poison gradients through jnp.where
        cnt = pt.layers.clip(pt.layers.reduce_sum(m, dim=1), 1.0, 1e9)
        mean = pt.layers.elementwise_div(summed, cnt, axis=0)
        h = pt.layers.fc(pt.layers.concat([mean, h_prev], axis=1),
                         size=H, act="tanh")
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    return x, rnn


def test_nested_matches_numpy_oracle():
    S, L = 4, 6
    x_var, rnn = _build(S, L)
    out = rnn()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    paragraphs = [
        [rng.randn(3, D), rng.randn(1, D), rng.randn(5, D)],
        [rng.randn(2, D), rng.randn(4, D)],
    ]
    (got,) = exe.run(feed={"x": _nested(paragraphs)}, fetch_list=[out],
                     return_numpy=False)
    params = sorted(v.name for v in pt.default_main_program().parameters())
    scope = pt.global_scope()
    w = np.asarray(scope.get([p for p in params if ".w" in p][0]))
    b = np.asarray(scope.get([p for p in params if ".b" in p][0]))
    data = np.asarray(got.data)
    off = 0
    for p in paragraphs:
        h = np.zeros((H,), np.float32)
        for sent in p:
            mean = np.asarray(sent, np.float32).mean(axis=0)
            h = np.tanh(np.concatenate([mean, h]) @ w + b)
            np.testing.assert_allclose(data[off], h, atol=1e-5)
            off += 1
    # output LoD: one token per sub-sequence
    lens = np.asarray(got.lengths)
    assert lens[0] == 3 and lens[1] == 2


def test_nested_final_memory_and_training():
    S, L = 3, 5
    x_var, rnn = _build(S, L)
    out = rnn()
    final = rnn.get_final_memory(0)
    label = pt.layers.data("label", shape=[-1, 1], dtype=np.int32,
                           append_batch_size=False)
    logits = pt.layers.fc(final, size=2)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(1)
    paragraphs = [
        [rng.randn(rng.randint(1, 5), D) for _ in range(rng.randint(1, 4))]
        for _ in range(4)
    ]
    # label = sign of the first sentence's first feature mean
    lab = np.array(
        [[int(np.asarray(p[0])[:, 0].mean() > 0)] for p in paragraphs],
        np.int32)
    lod = _nested(paragraphs)
    losses = []
    for _ in range(25):
        (l,) = exe.run(feed={"x": lod, "label": lab}, fetch_list=[loss])
        losses.append(float(l))
    assert losses[-1] < losses[0], losses[::8]


def test_uneven_subsequence_distribution_and_truncation():
    """Regression: sub ids are numbered globally across the batch, so a

    front-loaded sequence must not steal id space from later ones; and a
    sequence with more subs than max_subseqs truncates its output length."""
    S, L = 2, 4
    x_var, rnn = _build(S, L)
    out = rnn()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(3)
    paragraphs = [
        [rng.randn(2, D), rng.randn(1, D), rng.randn(3, D)],  # 3 subs > S
        [rng.randn(2, D), rng.randn(2, D)],                   # 2 subs
    ]
    (got,) = exe.run(feed={"x": _nested(paragraphs)}, fetch_list=[out],
                     return_numpy=False)
    lens = np.asarray(got.lengths)
    assert lens[0] == 2 and lens[1] == 2, lens  # truncated to S, not dropped
    # seq1's steps must match the oracle (its subs weren't lost)
    params = sorted(v.name for v in pt.default_main_program().parameters())
    scope = pt.global_scope()
    w = np.asarray(scope.get([p for p in params if ".w" in p][0]))
    b = np.asarray(scope.get([p for p in params if ".b" in p][0]))
    data = np.asarray(got.data)
    h = np.zeros((H,), np.float32)
    off = int(lens[0])
    for sent in paragraphs[1]:
        mean = np.asarray(sent, np.float32).mean(axis=0)
        h = np.tanh(np.concatenate([mean, h]) @ w + b)
        np.testing.assert_allclose(data[off], h, atol=1e-5)
        off += 1
