"""Native C++ runtime tests: recordio, prefetcher, master.

Reference test pattern (SURVEY §4.5): distributed machinery tested
in ONE process — Go master/pserver use in-memory/table tests
(go/master/service_internal_test.go), the C++ pserver starts server and
client in-process (pserver/test/test_ParameterServer2.cpp). Same here:
the native master is driven through ctypes in-process, including
timeout re-queue, failure eviction, and snapshot recovery.
"""

import os
import pickle
import time

import numpy as np
import pytest

native = pytest.importorskip("paddle_tpu.native")
from paddle_tpu.data.recordio import (  # noqa: E402
    dump_reader,
    master_reader,
    recordio_reader,
)


# --------------------------------------------------------------- recordio --
def test_recordio_roundtrip_multi_chunk(tmp_path):
    path = str(tmp_path / "a.rio")
    blobs = [os.urandom(np.random.randint(1, 70000)) for _ in range(64)]
    with native.RecordIOWriter(path) as w:
        for b in blobs:
            w.write(b)
    with native.RecordIOReader(path) as r:
        got = list(r)
    assert got == blobs
    assert native.num_records(path) == 64


def test_recordio_detects_corruption(tmp_path):
    path = str(tmp_path / "c.rio")
    with native.RecordIOWriter(path) as w:
        for i in range(10):
            w.write(b"x" * 1000)
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF  # flip a bit in a chunk body
    open(path, "wb").write(bytes(raw))
    with native.RecordIOReader(path) as r:
        with pytest.raises(IOError):
            list(r)


def test_prefetcher_propagates_shard_failure(tmp_path):
    good = str(tmp_path / "good.rio")
    with native.RecordIOWriter(good) as w:
        w.write(b"ok")
    with native.Prefetcher([good, str(tmp_path / "missing.rio")],
                           n_threads=1) as pf:
        with pytest.raises(IOError, match="cannot open"):
            list(pf)


def test_prefetcher_streams_all_shards(tmp_path):
    paths = []
    expect = set()
    for s in range(4):
        p = str(tmp_path / f"s{s}.rio")
        with native.RecordIOWriter(p) as w:
            for i in range(100):
                rec = f"{s}:{i}".encode()
                w.write(rec)
                expect.add(rec)
        paths.append(p)
    with native.Prefetcher(paths, n_threads=3, capacity=32) as pf:
        got = set(pf)
    assert got == expect


# ----------------------------------------------------------------- master --
def test_master_dispatch_finish_and_new_pass():
    with native.Master(timeout_s=30, max_failures=2) as m:
        m.set_dataset(["sh0", "sh1", "sh2"])
        seen = []
        while (t := m.get_task()) is not None:
            seen.append(t[1])
            m.task_finished(t[0])
        assert sorted(seen) == [b"sh0", b"sh1", b"sh2"]
        assert m.counts() == {"todo": 0, "pending": 0, "done": 3, "failed": 0}
        m.new_pass()
        assert m.counts()["todo"] == 3


def test_master_timeout_requeue_and_failure_eviction():
    with native.Master(timeout_s=0.2, max_failures=1) as m:
        m.add_task(b"t")
        tid, _ = m.get_task()
        assert m.get_task() is None  # pending, nothing to hand out
        time.sleep(0.25)
        tid2, _ = m.get_task()  # timed out → re-queued (failure 1)
        assert tid2 == tid
        m.task_failed(tid2)  # failure 2 > max_failures → evicted
        assert m.get_task() is None
        assert m.counts()["failed"] == 1


def test_master_snapshot_recovery(tmp_path):
    snap = str(tmp_path / "master.snap")
    m = native.Master(snapshot_path=snap, timeout_s=30, max_failures=2)
    m.set_dataset(["a", "b", "c"])
    tid, meta = m.get_task()
    m.task_finished(tid)
    t2 = m.get_task()  # left pending — simulates a dead worker
    m.snapshot()
    m.close()

    m2 = native.Master(snapshot_path=snap, timeout_s=30, max_failures=2)
    c = m2.counts()
    # done survives; the pending task returned to todo (worker died)
    assert c["done"] == 1 and c["todo"] == 2 and c["pending"] == 0
    m2.close()


# ------------------------------------------------------- reader pipeline --
def test_dump_and_readers_roundtrip(tmp_path):
    rng = np.random.RandomState(0)
    samples = [(rng.randn(4).astype(np.float32), int(i % 3)) for i in range(57)]

    def src():
        yield from samples

    paths = dump_reader(src, str(tmp_path / "data"), num_shards=3)
    assert len(paths) == 3

    got = list(recordio_reader(paths, n_threads=2)())
    assert len(got) == 57
    canon = lambda ss: sorted((s[0].tobytes(), s[1]) for s in ss)
    assert canon(got) == canon(samples)

    with native.Master(timeout_s=30) as m:
        got2 = list(master_reader(m, paths)())
        assert len(got2) == 57
        assert m.counts()["done"] == 3
