"""Elastic sharded checkpoints (ISSUE 14, paddle_tpu/pipeline/elastic).

Three contracts:
  * background sharded commit blocks only on an IN-FLIGHT previous
    commit — the capture is reference-only (jax.Array immutability is
    the snapshot), so submit latency is independent of model size and
    the values committed are the values at submit time even if training
    keeps mutating the scope;
  * resume-with-resharding — a dp8-saved checkpoint restores
    bit-identically onto a dp4x2 mesh and onto a 4-device mesh (the
    sharded format stores GLOBAL arrays, placement is re-derived);
  * a torn single shard costs one checkpoint interval, never the
    restore — typed corruption, quarantine, newest-VALID fallback.
"""

import json
import os
import time
import zipfile

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import parallel as pp
from paddle_tpu.obs.metrics import registry
from paddle_tpu.pipeline import elastic
from paddle_tpu.trainer import _CheckpointWriter


def _build(seed=5):
    pt.default_main_program().random_seed = seed
    pt.default_startup_program().random_seed = seed
    x = pt.layers.data("x", shape=[16])
    y = pt.layers.data("y", shape=[1])
    h = pt.layers.fc(x, size=32, act="relu",
                     param_attr=pt.ParamAttr(name="w1"), bias_attr=False)
    pred = pt.layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w2"),
                        bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
    return loss


def _feed(step):
    rng = np.random.RandomState(step)
    return {"x": rng.randn(16, 16).astype(np.float32),
            "y": rng.randn(16, 1).astype(np.float32)}


def _host_params():
    return {n: np.asarray(pt.global_scope().get(n))
            for n in sorted(pt.global_scope().keys())
            if not n.startswith("@")}


# ------------------------------------------------- background commit --


def test_submit_blocks_only_on_inflight_commit(tmp_path, monkeypatch):
    """The acceptance assertion: with a slow commit in flight, a fresh
    submit returns immediately (reference capture, no d2h, no disk);
    the NEXT submit drains the in-flight one first (double buffer)."""
    loss = _build()
    exe = pt.Executor()
    exe.run_startup(pt.default_startup_program())
    exe.run(feed=_feed(0), fetch_list=[loss])

    real_save = pio.save_checkpoint
    delay = 0.4

    def slow_save(*a, **kw):
        time.sleep(delay)
        return real_save(*a, **kw)

    monkeypatch.setattr(pio, "save_checkpoint", slow_save)
    writer = _CheckpointWriter()
    d = str(tmp_path / "ck")
    prog = pt.default_main_program()

    t0 = time.monotonic()
    elastic.submit_sharded_save(writer, d, trainer_args={"step": 1},
                                main_program=prog)
    first_submit = time.monotonic() - t0
    assert first_submit < delay / 2, (
        f"submit spent {first_submit:.3f}s — it must not wait for the "
        "commit it just enqueued")

    t0 = time.monotonic()
    elastic.submit_sharded_save(writer, d, trainer_args={"step": 2},
                                main_program=prog)
    second_submit = time.monotonic() - t0
    assert second_submit >= delay / 2, (
        "second submit returned before the in-flight commit drained — "
        "unbounded snapshot queue")
    writer.drain()
    assert writer.commits == 2 and writer.failures == 0
    assert pio.get_latest_checkpoint_serial(d) == 1


def test_snapshot_isolated_from_continued_training(tmp_path):
    """Values committed are the values AT SUBMIT TIME: training (or an
    outright overwrite) after submit must not leak into the commit."""
    loss = _build()
    exe = pt.Executor()
    exe.run_startup(pt.default_startup_program())
    exe.run(feed=_feed(0), fetch_list=[loss])
    at_submit = _host_params()

    writer = _CheckpointWriter()
    d = str(tmp_path / "ck")
    elastic.submit_sharded_save(writer, d, trainer_args={"step": 1},
                                main_program=pt.default_main_program())
    # mutate the live scope while the commit may still be in flight
    pt.global_scope().set("w1", np.zeros_like(at_submit["w1"]))
    writer.drain()

    pt.reset_global_scope()
    args = pio.load_checkpoint(d, pt.default_main_program())
    assert args == {"step": 1}
    got = _host_params()
    for n, v in at_submit.items():
        np.testing.assert_array_equal(v, got[n], err_msg=n)


def test_submit_refuses_multiprocess(monkeypatch):
    _build()
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    with pytest.raises(NotImplementedError, match="single-process"):
        elastic.submit_sharded_save(_CheckpointWriter(), "/tmp/nope")


# ---------------------------------------------------------- resharding --


def _train_on_mesh(mesh, steps):
    pt.reset()
    loss = _build()
    gb = pt.default_main_program().global_block()
    gb.var("w1").sharding = PartitionSpec(None, "mp") \
        if "mp" in mesh.axis_names else PartitionSpec()
    exe = pp.ParallelExecutor(mesh, shard_optimizer_state=True)
    pt.Executor().run(pt.default_startup_program())
    for s in range(steps):
        exe.run(pt.default_main_program(), feed=_feed(s),
                fetch_list=[loss])
    return loss


@pytest.mark.parametrize("target_spec", ["dp4,mp2", "dp4"])
def test_dp8_checkpoint_resumes_resharded_bitwise(tmp_path, target_spec):
    """dp8-saved params restore BIT-identically onto a dp4x2 mesh and
    onto a 4-device mesh (different device count via mesh prefix)."""
    assert len(jax.devices()) == 8
    mesh8 = pp.make_mesh((8,), ("dp",))
    _train_on_mesh(mesh8, 2)
    saved = _host_params()
    d = str(tmp_path / "ck")
    pio.save_checkpoint(d, {"step": 2}, pt.default_main_program(),
                        sharded=True)

    pt.reset_global_scope()
    target = pp.mesh_from_spec(target_spec)
    args = elastic.load_checkpoint_resharded(
        d, pt.default_main_program(), mesh=target)
    assert args == {"step": 2}
    got = _host_params()
    assert set(got) == set(saved)
    for n, v in saved.items():
        np.testing.assert_array_equal(v, got[n], err_msg=n)
    # and the restored state actually lives on the target mesh
    w1 = pt.global_scope().get("w1")
    assert set(w1.sharding.mesh.axis_names) == set(target.axis_names)


def test_world_change_counts_reshard(tmp_path):
    """sharded_meta.json records the saving world; loading under a
    different one increments pt_ckpt_reshard_total."""
    loss = _build()
    pt.Executor().run(pt.default_startup_program())
    pt.Executor().run(feed=_feed(0), fetch_list=[loss])
    d = str(tmp_path / "ck")
    pio.save_checkpoint(d, {"step": 1}, pt.default_main_program(),
                        sharded=True)
    sd = os.path.join(d, "checkpoint_0")
    meta_path = os.path.join(sd, pio.SHARDED_META)
    with open(meta_path) as f:
        meta = json.load(f)
    assert meta["world"]["device_count"] == jax.device_count()

    before = registry().counter_value(elastic.RESHARD_COUNTER) or 0.0
    pio.load_sharded_checkpoint(sd, pt.default_main_program())
    assert registry().counter_value(elastic.RESHARD_COUNTER) == before

    # rewrite the recorded world: now it's an elastic restore.
    # (sha256 integrity covers payload files, not the manifest itself,
    # so the edit keeps the serial loadable — mirror any hash update
    # here if that ever changes.)
    meta["world"]["device_count"] = 9999
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    pio.load_sharded_checkpoint(sd, pt.default_main_program())
    assert registry().counter_value(elastic.RESHARD_COUNTER) == before + 1


# ------------------------------------------------- torn-shard fallback --


def _two_serials(tmp_path):
    loss = _build()
    exe = pt.Executor()
    exe.run_startup(pt.default_startup_program())
    d = str(tmp_path / "ck")
    prog = pt.default_main_program()
    exe.run(feed=_feed(0), fetch_list=[loss])
    pio.save_checkpoint(d, {"step": 1}, prog, sharded=True)
    good = _host_params()
    exe.run(feed=_feed(1), fetch_list=[loss])
    pio.save_checkpoint(d, {"step": 2}, prog, sharded=True)
    return d, good


def test_torn_shard_quarantines_and_falls_back(tmp_path):
    d, good = _two_serials(tmp_path)
    shard = os.path.join(d, "checkpoint_1", "shards_p0.npz")
    with open(shard, "r+b") as f:  # tear the newest serial's one shard
        f.truncate(max(os.path.getsize(shard) // 2, 8))

    # read-only probe: newest COMPLETE is 1, newest VALID is 0
    assert pio.get_latest_checkpoint_serial(d) == 1
    assert pio.get_latest_checkpoint_serial(d, verify=True) == 0
    assert os.path.exists(os.path.join(d, "checkpoint_1"))  # no side effect

    with pytest.warns(UserWarning, match="quarantined"):
        args = pio.load_checkpoint(d, pt.default_main_program())
    assert args == {"step": 1}
    assert not os.path.exists(os.path.join(d, "checkpoint_1"))
    assert os.path.exists(os.path.join(d, "checkpoint_1.corrupt"))
    got = _host_params()
    for n, v in good.items():
        np.testing.assert_array_equal(v, got[n], err_msg=n)


def test_verify_detects_flipped_payload_byte(tmp_path):
    d, _ = _two_serials(tmp_path)
    shard = os.path.join(d, "checkpoint_1", "shards_p0.npz")
    with open(shard, "r+b") as f:
        f.seek(os.path.getsize(shard) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    with pytest.raises(pio.CheckpointCorruptError, match="sha256"):
        pio.verify_checkpoint(os.path.join(d, "checkpoint_1"))
    assert pio.get_latest_checkpoint_serial(d, verify=True) == 0


def test_missing_shard_member_is_typed(tmp_path):
    """A stale/truncated shard file that still opens as a zip raises the
    TYPED CheckpointCorruptError (so load_checkpoint can fall back), not
    a bare KeyError."""
    d, _ = _two_serials(tmp_path)
    sd = os.path.join(d, "checkpoint_1")
    shard = os.path.join(sd, "shards_p0.npz")
    # rebuild the archive with one member dropped
    with zipfile.ZipFile(shard) as z:
        names = z.namelist()
        keep = {n: z.read(n) for n in names[:-1]}
    with zipfile.ZipFile(shard, "w") as z:
        for n, blob in keep.items():
            z.writestr(n, blob)
    with pytest.raises(pio.CheckpointCorruptError,
                       match="missing member|uncovered"):
        pio.load_sharded_checkpoint(sd, pt.default_main_program())
