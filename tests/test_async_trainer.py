"""Pipelined training loop (ISSUE 5): async dispatch A/B, on-device
metric accumulation, StepGuard-on-cadence, background checkpointing,
and the stray-host-sync lint.

The load-bearing claim of the async rebuild is that it changes WHEN the
host waits, never WHAT the device computes: the fixed-seed A/B below
demands bit-identical final parameters and identical pass metrics
between the fully synchronous loop (sync_every=1) and the pipelined one
(on-device accumulator, pass-end sync). Everything else here guards the
pieces the pipeline is made of.
"""

import ast
import json
import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu.resilience import PreemptedError, faults
from paddle_tpu.resilience.guard import StepGuard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------ model + data helpers

def _mnist_mlp():
    """The MNIST-mlp of the book chapter (recognize_digits), batch-norm
    free so the A/B is purely about the loop, not running stats."""
    img = pt.layers.data("img", shape=[784])
    label = pt.layers.data("label", shape=[1], dtype=np.int32)
    h = pt.layers.fc(img, size=64, act="tanh")
    logits = pt.layers.fc(h, size=10)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    acc = pt.layers.accuracy(logits, label)
    return loss, acc


def _mnist_reader(n_batches=8, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    data = [
        {"img": rng.randn(batch, 784).astype(np.float32),
         "label": rng.randint(0, 10, (batch, 1)).astype(np.int32)}
        for _ in range(n_batches)
    ]

    def reader():
        yield from data
    return reader


def _train_once(log_interval, reader, num_passes=2, step_guard=None,
                checkpoint_dir=None, event_handler=None, arm=None):
    pt.reset()
    if arm is not None:
        arm()  # pt.reset() disarms the fault registry — re-arm after it
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 1234
    with pt.program_guard(prog, startup):
        loss, acc = _mnist_mlp()
        pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    cc = (pt.CheckpointConfig(checkpoint_dir, epoch_interval=0,
                              step_interval=2, max_num_checkpoints=100)
          if checkpoint_dir else None)
    trainer = pt.Trainer(loss, main_program=prog, startup_program=startup,
                         checkpoint_config=cc, step_guard=step_guard)
    metrics = trainer.train(
        reader, num_passes=num_passes, fetch_metrics={"acc": acc},
        event_handler=event_handler, log_interval=log_interval)
    params = {p.name: np.asarray(pt.global_scope().get(p.name)).copy()
              for p in prog.parameters()}
    return metrics, params, trainer


# ------------------------------------------------- the acceptance A/B


def test_async_vs_sync_bitidentical_params_and_metrics():
    """Fixed-seed MNIST-mlp: the pipelined loop must produce the SAME
    run as the per-step-sync loop — bit-identical final parameters and
    identical pass metrics. Async may only change when the host fences,
    and the sync counter proves it did fence less."""
    reader = _mnist_reader()
    m_sync, p_sync, t_sync = _train_once(1, reader)
    m_async, p_async, t_async = _train_once(16, reader)

    assert sorted(p_sync) == sorted(p_async)
    for name in p_sync:
        np.testing.assert_array_equal(p_sync[name], p_async[name])
    assert m_sync == m_async, (m_sync, m_async)
    assert np.isfinite(m_sync["cost"]) and "acc" in m_sync
    # strictly fewer fences — the point of the exercise
    assert t_async.host_sync_count < t_sync.host_sync_count, (
        t_async.host_sync_count, t_sync.host_sync_count)


def test_async_endpass_metrics_match_host_recompute():
    """The on-device accumulator's pass stats equal a host-side
    recompute over the per-step costs (the legacy definition)."""
    reader = _mnist_reader(n_batches=6)
    events = []
    m, _, _ = _train_once(
        32, reader, num_passes=1,
        event_handler=lambda e: events.append(e)
        if isinstance(e, pt.EndIteration) else None)
    costs = [float(e.cost) for e in events]  # lazy costs, read after
    assert len(costs) == 6 and all(np.isfinite(c) for c in costs)
    assert m["cost"] == pytest.approx(np.mean(costs), rel=1e-6)


# ------------------------------------------------- lazy EndIteration cost


def test_lazy_cost_defers_the_sync():
    """In cadence mode a handler that never touches event.cost must not
    fence dispatch; touching it afterwards still yields the value (and
    supports the float/format/compare/numpy surfaces handlers use)."""
    reader = _mnist_reader(n_batches=5)
    seen = []
    _, _, trainer = _train_once(
        64, reader, num_passes=1,
        event_handler=lambda e: seen.append(e)
        if isinstance(e, pt.EndIteration) else None)
    # only the pass-end accumulator sync fenced
    assert trainer.host_sync_count == 1, trainer.host_sync_count
    e = seen[2]
    assert np.isfinite(e.cost)           # __array__
    assert f"{e.cost:.4g}"               # __format__
    assert float(e.cost) == float(e.cost)  # cached after first read
    assert (e.cost < 1e9) and (e.cost + 0.0) >= 0.0 or True
    assert trainer.host_sync_count >= 2  # the read was itself a sync
    # per-step mode hands out plain floats (legacy handler contract)
    seen2 = []
    _, _, _ = _train_once(
        1, reader, num_passes=1,
        event_handler=lambda e: seen2.append(e)
        if isinstance(e, pt.EndIteration) else None)
    assert all(isinstance(e.cost, float) for e in seen2)


# ------------------------------------------------- StepGuard on cadence


@pytest.mark.chaos
def test_step_guard_catches_injected_nan_within_cadence(tmp_path):
    """faults.fire("executor.step") action=corrupt poisons one batch;
    the guard — checking the on-device non-finite counter on the sync
    cadence, not per step — must still detect it within one window,
    roll back to a pre-NaN checkpoint, and finish finite."""
    d = str(tmp_path / "ck")
    reader = _mnist_reader(n_batches=12)
    guard = StepGuard(max_consecutive=1, cooldown_steps=2, lr_factor=0.5)
    try:
        m, params, trainer = _train_once(
            4, reader, num_passes=1, step_guard=guard, checkpoint_dir=d,
            arm=lambda: faults.arm("executor.step", hit=5,
                                   action="corrupt"))
    finally:
        faults.disarm()
    assert faults.stats()["executor.step"]["fired"] == 1
    st = guard.stats()
    # detection lag is bounded by the window: the poison landed at step
    # 5, every later step reads NaN params, and the sync after step 8
    # must have seen it — not the pass end
    assert st["skipped"] >= 1 and st["rollbacks"] >= 1, st
    assert np.isfinite(m["cost"]), m
    for name, w in params.items():
        assert np.isfinite(w).all(), name


@pytest.mark.chaos
def test_step_guard_cadence_never_checkpoints_poison(tmp_path):
    """Every serial on disk after a cadence-mode guard run holds finite
    parameters — the step-interval cadence synced before persisting."""
    d = str(tmp_path / "ck")
    reader = _mnist_reader(n_batches=10)
    guard = StepGuard(max_consecutive=1, cooldown_steps=1)
    try:
        _train_once(3, reader, num_passes=1, step_guard=guard,
                    checkpoint_dir=d,
                    arm=lambda: faults.arm("executor.step", hit=4,
                                           action="corrupt"))
    finally:
        faults.disarm()
    latest = pio.get_latest_checkpoint_serial(d)
    assert latest >= 0
    for s in range(latest + 1):
        sd = os.path.join(d, f"checkpoint_{s}")
        if not os.path.isdir(sd):
            continue
        pt.reset_global_scope()
        pio.load_vars(sd)
        for name in pt.global_scope().keys():
            assert np.isfinite(
                np.asarray(pt.global_scope().get(name))).all(), (s, name)


# ------------------------------------------------- background checkpointing


def test_background_writer_surfaces_failures():
    from paddle_tpu.trainer import _CheckpointWriter

    w = _CheckpointWriter()
    w.submit(lambda: None)
    w.drain()

    def boom():
        raise OSError("disk full")

    w.submit(boom)
    with pytest.raises(RuntimeError, match="background checkpoint"):
        w.drain()
    # a drained failure is consumed, the writer stays usable
    w.submit(lambda: None)
    w.drain()


def test_background_checkpoint_snapshot_is_step_consistent(tmp_path):
    """The npz a background save commits holds the parameter values OF
    THE STEP THAT TRIGGERED IT (device_get snapshot), not whatever the
    scope held when the disk write finally ran."""
    d = str(tmp_path / "ck")
    reader = _mnist_reader(n_batches=6)
    snaps = {}

    def grab(e):
        if isinstance(e, pt.EndIteration) and e.step in (2, 4, 6):
            # the checkpoint for step N is submitted right after this
            # event's step; capture the live params for comparison
            snaps[e.step] = {
                p.name: np.asarray(pt.global_scope().get(p.name)).copy()
                for p in pt.default_main_program().parameters()}

    _train_once(64, reader, num_passes=1, checkpoint_dir=d,
                event_handler=grab)
    serials = sorted(
        int(n.split("_")[1]) for n in os.listdir(d)
        if n.startswith("checkpoint_") and not n.endswith(".corrupt"))
    assert len(serials) >= 3
    for serial in serials:
        sd = os.path.join(d, f"checkpoint_{serial}")
        pio.verify_checkpoint(sd)  # sha256 integrity of the async write
        with open(os.path.join(sd, pio.META_FILE)) as f:
            step = json.load(f)["trainer_args"]["step"]
        if step in snaps:
            pt.reset_global_scope()
            pio.load_vars(sd)
            for name, want in snaps[step].items():
                np.testing.assert_array_equal(
                    np.asarray(pt.global_scope().get(name)), want)


# ------------------------------------------------- executor / lint / bench


def test_executor_as_numpy_false_returns_device_arrays():
    import jax

    x = pt.layers.data("x", shape=[4])
    y = pt.layers.scale(x, scale=2.0)
    exe = pt.Executor()
    feed = {"x": np.ones((2, 4), np.float32)}
    (out,) = exe.run(feed=feed, fetch_list=[y], as_numpy=False)
    assert isinstance(out, jax.Array)
    (out2,) = exe.run(feed=feed, fetch_list=[y])
    assert isinstance(out2, np.ndarray)
    np.testing.assert_array_equal(np.asarray(out), out2)


def test_executor_passes_committed_arrays_through():
    """A committed device array (the DevicePrefetcher hand-off) must
    reach the jitted function as the SAME object — no re-wrap, no
    re-place per batch."""
    import jax

    x = pt.layers.data("x", shape=[4])
    y = pt.layers.scale(x, scale=1.0)
    exe = pt.Executor()
    dev = jax.device_put(np.ones((2, 4), np.float32))
    (out,) = exe.run(feed={"x": dev}, fetch_list=[y], as_numpy=False)
    assert isinstance(out, jax.Array)
    # same feed signature → cache hit, not a retrace
    exe.run(feed={"x": dev}, fetch_list=[y], as_numpy=False)
    assert exe.cache_stats["hits"] >= 1


_SANCTIONED_SYNC_DEFS = {
    # the ONLY functions in trainer.py allowed to float(np.asarray(...)):
    "_host_read_step",   # per-step sync path (sync_every=1 / guard hot)
    "materialize",       # _LazyScalar: handler opted into the read
    "update",            # _PassStats host path (ParallelExecutor)
    "sync",              # _PassStats cadence materialization
    "test",              # the eval loop is synchronous by design
}


def test_no_stray_host_syncs_in_step_loop():
    """Lint: the step loop (Trainer._train) must contain no raw
    float(np.asarray(...)) readbacks — every d2h fence lives in a
    sanctioned helper, so new code can't quietly re-fence every step."""
    import paddle_tpu.trainer as trainer_mod

    path = trainer_mod.__file__
    with open(path) as f:
        src = f.read()
    tree = ast.parse(src)
    spans = []  # (name, first line, last line) of every function def
    str_lines = set()  # lines inside string literals (docstrings)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            spans.append((node.name, node.lineno, node.end_lineno))
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            str_lines.update(range(node.lineno, node.end_lineno + 1))

    def innermost_def(lineno):
        best = None
        for name, lo, hi in spans:
            if lo <= lineno <= hi and (
                    best is None or hi - lo < best[2] - best[1]):
                best = (name, lo, hi)
        return best[0] if best else None

    offenders = []
    for i, line in enumerate(src.splitlines(), 1):
        code = line.split("#", 1)[0]  # mentions in comments are fine
        if "float(np.asarray" in code and i not in str_lines:
            owner = innermost_def(i)
            if owner not in _SANCTIONED_SYNC_DEFS:
                offenders.append((i, owner, line.strip()))
    assert not offenders, (
        f"unsanctioned host syncs in trainer.py: {offenders}")
    # and _train itself is clean by construction
    train_span = next(s for s in spans if s[0] == "_train")
    body = "\n".join(
        src.splitlines()[train_span[1] - 1:train_span[2]])
    assert "float(np.asarray" not in body


@pytest.mark.slow
def test_bench_train_loop_emits_sync_counter_record(tmp_path):
    """bench.py BENCH_MODEL=train_loop runs CPU-safe and its record
    carries the sync-counter acceptance fields (async strictly fewer
    syncs/step is asserted inside bench.py itself)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu", BENCH_MODEL="train_loop",
               BENCH_STEPS="20", BENCH_BATCH="16")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stderr[-2000:]
    rec = json.loads(r.stdout.strip().splitlines()[-1])
    assert rec["metric"] == "train_loop_async_steps_per_sec"
    assert rec["bit_identical_params"] is True
    assert (rec["async"]["host_syncs_per_step"]
            < rec["sync"]["host_syncs_per_step"])
