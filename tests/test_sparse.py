"""Sparse input slots + SelectedRows (row-wise) gradient tests.

Reference parity targets:
- paddle/py_paddle/dataprovider_converter.py:154,184 (SparseBinaryScanner /
  SparseFloatScanner) — sparse feed slots.
- paddle/math/CpuSparseMatrix.h — sparse x dense matmul semantics.
- paddle/framework/selected_rows.h + lookup_table_op.cc (is_sparse) — rows+
  values gradients with lazy optimizer updates.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.sparse import SelectedRows, SparseArray
from paddle_tpu.data.feeder import DataFeeder


# ------------------------------------------------------- SparseArray core --
def test_sparse_array_binary_to_dense():
    samples = [[0, 3], [2], [], [1, 3]]
    sa = SparseArray.from_batch(samples, dim=4, format="binary", bucket=8)
    dense = np.asarray(sa.to_dense())
    want = np.zeros((4, 4), np.float32)
    for r, idxs in enumerate(samples):
        for i in idxs:
            want[r, i] = 1.0
    np.testing.assert_allclose(dense, want)


def test_sparse_array_float_to_dense_and_matmul():
    samples = [[(0, 0.5), (2, -1.5)], [(1, 2.0)]]
    sa = SparseArray.from_batch(samples, dim=3, format="float", bucket=8)
    dense = np.asarray(sa.to_dense())
    want = np.array([[0.5, 0, -1.5], [0, 2.0, 0]], np.float32)
    np.testing.assert_allclose(dense, want)
    w = np.random.RandomState(0).randn(3, 5).astype(np.float32)
    np.testing.assert_allclose(
        np.asarray(sa.matmul(w)), want @ w, rtol=1e-5, atol=1e-5
    )


def test_sparse_array_index_out_of_range():
    with pytest.raises(ValueError):
        SparseArray.from_batch([[7]], dim=4, format="binary")


def test_selected_rows_dedup_sums_duplicates():
    rows = np.array([2, 0, 2, 5], np.int32)  # 5 == num_rows → padding
    vals = np.arange(8, dtype=np.float32).reshape(4, 2)
    sr = SelectedRows(rows, vals, num_rows=5)
    dense = np.asarray(sr.to_dense())
    want = np.zeros((5, 2), np.float32)
    want[2] = vals[0] + vals[2]
    want[0] = vals[1]
    np.testing.assert_allclose(dense, want)
    uniq, summed = sr.dedup()
    redense = np.zeros((5, 2), np.float32)
    for r, v in zip(np.asarray(uniq), np.asarray(summed)):
        if r < 5:
            redense[r] += v
    np.testing.assert_allclose(redense, want)


# ------------------------------------------------------------ feeder path --
def test_feeder_builds_sparse_slots():
    pt.reset()
    with pt.program_guard(pt.Program(), pt.Program()):
        xs = pt.layers.data("xs", shape=[6], sparse_format="binary")
        xf = pt.layers.data("xf", shape=[6], sparse_format="float")
        y = pt.layers.data("y", shape=[1], dtype=np.int32)
        feeder = DataFeeder([xs, xf, y], bucket=16)
    batch = [
        ([0, 2], [(1, 0.5)], [1]),
        ([5], [(4, -2.0), (0, 1.0)], [0]),
    ]
    feed = feeder.feed(batch)
    assert isinstance(feed["xs"], SparseArray)
    assert isinstance(feed["xf"], SparseArray)
    assert feed["xs"].batch == 2 and feed["xs"].dim == 6
    np.testing.assert_allclose(
        np.asarray(feed["xs"].to_dense())[1], [0, 0, 0, 0, 0, 1]
    )
    np.testing.assert_allclose(
        np.asarray(feed["xf"].to_dense())[1], [1.0, 0, 0, 0, -2.0, 0]
    )
    assert feed["y"].shape == (2, 1)


# --------------------------------------------- sparse fc forward/backward --
def _fc_program(sparse: bool, dim=8, out=4):
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        if sparse:
            x = pt.layers.data("x", shape=[dim], sparse_format="binary")
        else:
            x = pt.layers.data("x", shape=[dim])
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        logits = pt.layers.fc(x, size=out, param_attr=pt.ParamAttr(name="W"),
                              bias_attr=False)
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label)
        )
        pt.optimizer.SGD(learning_rate=0.5).minimize(loss)
    return prog, startup, loss


def test_sparse_fc_matches_dense_fc():
    """Same model fed sparse vs dense must produce identical loss and an
    identical W gradient step (the CpuSparseMatrix::mul equivalence)."""
    samples = [[0, 3, 7], [2], [1, 5]]
    dense_x = np.zeros((3, 8), np.float32)
    for r, idxs in enumerate(samples):
        dense_x[r, idxs] = 1.0
    label = np.array([[0], [1], [2]], np.int32)

    results = {}
    for sparse in (False, True):
        pt.reset()
        prog, startup, loss = _fc_program(sparse)
        prog.random_seed = startup.random_seed = 3
        exe = pt.Executor()
        exe.run(startup)
        if sparse:
            x = SparseArray.from_batch(samples, dim=8, format="binary",
                                       bucket=16)
        else:
            x = dense_x
        (l,) = exe.run(prog, feed={"x": x, "label": label},
                       fetch_list=[loss])
        results[sparse] = (float(l), np.asarray(pt.global_scope().get("W")))

    assert results[True][0] == pytest.approx(results[False][0], rel=1e-5)
    np.testing.assert_allclose(
        results[True][1], results[False][1], rtol=1e-5, atol=1e-6
    )


# ------------------------------------------- SelectedRows embedding grads --
def _emb_program(is_sparse: bool, optimizer):
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        ids = pt.layers.data("ids", shape=[4], dtype=np.int32,
                             append_batch_size=True)
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        emb = pt.layers.embedding(
            ids, size=(50, 6), is_sparse=is_sparse,
            param_attr=pt.ParamAttr(name="emb_w"),
        )
        pooled = pt.layers.reduce_mean(emb, dim=1)
        logits = pt.layers.fc(pooled, size=3)
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label)
        )
        optimizer().minimize(loss)
    return prog, startup, loss


def _run_emb(is_sparse, optimizer, steps=3):
    pt.reset()
    prog, startup, loss = _emb_program(is_sparse, optimizer)
    prog.random_seed = startup.random_seed = 11
    exe = pt.Executor()
    exe.run(startup)
    rng = np.random.RandomState(0)
    w0 = np.asarray(pt.global_scope().get("emb_w")).copy()
    # fixed batch: loss must fall monotonically-ish when overfitting it
    ids = rng.randint(0, 20, (4, 4)).astype(np.int32)  # rows < 20 only
    label = rng.randint(0, 3, (4, 1)).astype(np.int32)
    losses = []
    for s in range(steps):
        (l,) = exe.run(prog, feed={"ids": ids, "label": label},
                       fetch_list=[loss])
        losses.append(float(l))
    w1 = np.asarray(pt.global_scope().get("emb_w"))
    return w0, w1, losses


def test_sparse_embedding_sgd_matches_dense_grad():
    """SGD is linear in the gradient, so SelectedRows (row-wise) updates
    must match the dense-scatter path bit-for-bit-ish."""
    w0d, w1d, ld = _run_emb(False, lambda: pt.optimizer.SGD(0.5))
    w0s, w1s, ls = _run_emb(True, lambda: pt.optimizer.SGD(0.5))
    np.testing.assert_allclose(w0d, w0s)  # same init
    np.testing.assert_allclose(ld, ls, rtol=1e-5)
    np.testing.assert_allclose(w1d, w1s, rtol=1e-4, atol=1e-6)


def test_sparse_embedding_adam_is_lazy():
    """Lazy adam must (a) train, (b) leave never-touched rows exactly at
    their init, while dense adam drifts every row every step."""
    w0s, w1s, ls = _run_emb(True, lambda: pt.optimizer.Adam(0.05), steps=5)
    assert ls[-1] < ls[0]
    untouched = slice(20, 50)  # ids were drawn < 20
    np.testing.assert_allclose(w1s[untouched], w0s[untouched])
    assert not np.allclose(w1s[:20], w0s[:20])  # touched rows moved
    # and the touched-row trajectory matches dense adam (moments start at
    # zero, so on a repeated batch lazy == dense for every touched row)
    w0d, w1d, ld = _run_emb(False, lambda: pt.optimizer.Adam(0.05), steps=5)
    np.testing.assert_allclose(ld, ls, rtol=1e-4)
    np.testing.assert_allclose(w1d[:20], w1s[:20], rtol=1e-3, atol=1e-6)


def test_sparse_embedding_momentum_and_adagrad_train():
    for opt in (lambda: pt.optimizer.Momentum(0.1, 0.9),
                lambda: pt.optimizer.Adagrad(0.1)):
        w0, w1, ls = _run_emb(True, opt, steps=4)
        assert ls[-1] < ls[0]
        np.testing.assert_allclose(w1[30:], w0[30:])


def test_sparse_fields_survive_program_roundtrip():
    """to_dict/from_dict must carry sparse_update and sparse_format — a
    restored program losing them would silently densify embedding grads /
    break sparse feeding."""
    pt.reset()
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        x = pt.layers.data("x", shape=[16], sparse_format="binary")
        ids = pt.layers.data("ids", shape=[4], dtype=np.int32)
        emb = pt.layers.embedding(ids, size=(10, 4), is_sparse=True,
                                  param_attr=pt.ParamAttr(name="w_sp"))
    restored = pt.Program.from_dict(prog.to_dict())
    gb = restored.global_block()
    assert gb.var("x").sparse_format == "binary"
    assert gb.var("w_sp").sparse_update is True
    assert gb.var("ids").sparse_format is None


def test_sparse_embedding_rejects_tied_weight_use():
    """A sparse_update table consumed by any non-lookup op (tied-embedding
    output projection) must be rejected loudly — its gradient contribution
    would otherwise silently vanish."""
    pt.reset()
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        ids = pt.layers.data("ids", shape=[4], dtype=np.int32)
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        emb = pt.layers.embedding(ids, size=(30, 6), is_sparse=True,
                                  param_attr=pt.ParamAttr(name="tied_w"))
        pooled = pt.layers.reduce_mean(emb, dim=1)
        w = prog.global_block().var("tied_w")
        logits = pt.layers.matmul(pooled, w, transpose_y=True)  # tied use
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label)
        )
        pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(startup)
    with pytest.raises((ValueError, RuntimeError), match="sparse_update"):
        exe.run(prog,
                feed={"ids": np.zeros((2, 4), np.int32),
                      "label": np.zeros((2, 1), np.int32)},
                fetch_list=[loss])


def test_sparse_embedding_with_lod_input():
    """Ragged ids (LoD) through a sparse-update embedding: padding tokens
    must not perturb row 0 (they are pointed out of range)."""
    pt.reset()
    prog, startup = pt.Program(), pt.Program()
    with pt.program_guard(prog, startup):
        words = pt.layers.data("words", shape=[-1], dtype=np.int32,
                               lod_level=1, append_batch_size=False)
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        emb = pt.layers.embedding(words, size=(40, 8), is_sparse=True,
                                  param_attr=pt.ParamAttr(name="emb_w"))
        pooled = pt.layers.sequence_pool(emb, "sum")
        logits = pt.layers.fc(pooled, size=2)
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label)
        )
        pt.optimizer.SGD(1.0).minimize(loss)
    prog.random_seed = startup.random_seed = 5
    exe = pt.Executor()
    exe.run(startup)
    from paddle_tpu.core.lod import LoDArray

    w0 = np.asarray(pt.global_scope().get("emb_w")).copy()
    # sequences use only ids 10..19; id 0 must stay untouched even though
    # LoD padding slots hold 0
    rng = np.random.RandomState(2)
    seqs = [rng.randint(10, 20, (3,)).astype(np.int32),
            rng.randint(10, 20, (5,)).astype(np.int32)]
    lod = LoDArray.from_sequences(seqs, capacity=16, max_seqs=2)
    label = np.array([[0], [1]], np.int32)
    (l,) = exe.run(prog, feed={"words": lod, "label": label},
                   fetch_list=[loss])
    assert np.isfinite(l)
    w1 = np.asarray(pt.global_scope().get("emb_w"))
    np.testing.assert_allclose(w1[0], w0[0])  # padding did not touch row 0
    assert not np.allclose(w1[10:20], w0[10:20])
