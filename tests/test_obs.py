"""Run-wide observability (ISSUE 8): span tracing + unified metrics.

The contract under test, in four layers:

1. trace.py — spans nest and never cross threads, ring overflow drops
   the OLDEST events and counts them (pt_trace_dropped_total — no
   silent truncation), exported JSON is valid Chrome trace-event format
   (schema-checked), disarmed tracing is a single-boolean no-op and an
   AST lint bans armed-path work (kwargs dicts, context mutation)
   outside `_armed` guards on the hot loops.
2. metrics.py — one process-wide registry: Prometheus-compliant render
   (HELP/TYPE once per family, escaped label values), counters
   pre-registered so scrapers never see a missing series, the trainer/
   guard/checkpoint-writer/fault families ride the same scrape the
   serving histograms do.
3. promparse.py — the renderer round-trips through the strict parser;
   the tier-1 smoke test scrapes /metrics twice and asserts every
   family parses and every counter is monotonic.
4. correlation — request_id appears on every span of a served
   generation request (queue→admit→pool-step→stream), step/window ids
   link prefetch→enqueue→hostSync→checkpoint across threads, and the
   mixed-run acceptance exports ONE trace with spans on >= 4 threads.
"""

import ast
import json
import threading
import time
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import obs, profiler
from paddle_tpu.obs import promparse
from paddle_tpu.obs import trace as obs_trace
from paddle_tpu.obs.metrics import registry

# ----------------------------------------------------------------- helpers --


def _spans(doc):
    return [e for e in doc["traceEvents"] if e["ph"] == "X"]


def _instants(doc):
    return [e for e in doc["traceEvents"] if e["ph"] == "i"]


def _assert_nested_per_thread(doc):
    """Chrome X events on one tid must form a proper nesting: sorted by
    start, a later span either starts after the previous ends or lies
    entirely inside it."""
    by_tid = {}
    for e in _spans(doc):
        by_tid.setdefault(e["tid"], []).append((e["ts"], e["ts"] + e["dur"]))
    for tid, ivs in by_tid.items():
        stack = []
        for s, t in sorted(ivs, key=lambda it: (it[0], -it[1])):
            while stack and s >= stack[-1] - 1e-6:
                stack.pop()
            assert not stack or t <= stack[-1] + 1e-6, (
                f"tid {tid}: span [{s}, {t}] crosses enclosing span "
                f"ending at {stack[-1]}")
            stack.append(t)


# ------------------------------------------------------------------- trace --


def test_disarmed_hooks_are_noops():
    assert not obs_trace.armed()
    s1 = obs_trace.span("a", x=1)
    s2 = obs_trace.span("b")
    assert s1 is s2  # the shared null singleton: no per-call allocation
    with s1:
        pass
    obs_trace.instant("i", y=2)
    obs_trace.counter("c", 3)
    obs_trace.set_context(step=9)
    assert obs_trace.get_context() == {}


def test_span_nesting_and_context_args():
    with obs_trace.tracing() as tr:
        obs_trace.set_context(step=7)
        with obs_trace.span("outer", cat="t"):
            with obs_trace.span("inner", cat="t", extra=1):
                time.sleep(0.001)
        obs_trace.instant("mark")
    doc = tr.to_chrome()
    assert obs_trace.validate_chrome_trace(doc) == []
    _assert_nested_per_thread(doc)
    spans = {e["name"]: e for e in _spans(doc)}
    assert set(spans) == {"outer", "inner"}
    # sticky thread context lands on every event; explicit args merge in
    assert spans["outer"]["args"]["step"] == 7
    assert spans["inner"]["args"] == {"step": 7, "extra": 1}
    (mark,) = _instants(doc)
    assert mark["args"]["step"] == 7
    # inner is contained in outer on the same tid
    assert spans["inner"]["tid"] == spans["outer"]["tid"]
    assert spans["inner"]["ts"] >= spans["outer"]["ts"]
    assert (spans["inner"]["ts"] + spans["inner"]["dur"]
            <= spans["outer"]["ts"] + spans["outer"]["dur"] + 1e-6)


def test_spans_never_cross_threads():
    """Each thread's spans land in its own ring with its own tid; the
    per-thread context never leaks to another thread."""
    def work(n):
        obs_trace.set_context(worker=n)
        with obs_trace.span(f"w{n}", cat="t"):
            time.sleep(0.002)

    with obs_trace.tracing() as tr:
        threads = [threading.Thread(target=work, args=(n,), name=f"obs-w{n}")
                   for n in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    doc = tr.to_chrome()
    assert obs_trace.validate_chrome_trace(doc) == []
    _assert_nested_per_thread(doc)
    spans = _spans(doc)
    assert len(spans) == 4
    assert len({e["tid"] for e in spans}) == 4
    names = {e["name"]: e for e in spans}
    for n in range(4):
        assert names[f"w{n}"]["args"]["worker"] == n
    # thread-name metadata is emitted per ring
    meta = {e["args"]["name"] for e in doc["traceEvents"]
            if e["ph"] == "M"}
    assert {f"obs-w{n}" for n in range(4)} <= meta


def test_ring_overflow_drops_oldest_and_counts():
    with obs_trace.tracing(ring_size=8) as tr:
        for i in range(20):
            obs_trace.instant("ev", i=i)
        assert tr.dropped_total() == 12
        doc = tr.to_chrome()
        kept = [e["args"]["i"] for e in _instants(doc)]
        assert kept == list(range(12, 20))  # oldest dropped, newest kept
        assert doc["otherData"]["dropped_events"] == 12
        # the drop counter is scrapeable while armed...
        fams = promparse.parse_text(registry().render())
        assert fams["pt_trace_dropped_total"].value() >= 12
        assert fams["pt_trace_armed"].value() == 1
    # ...and survives the session ending (monotonic across sessions)
    assert obs_trace.dropped_total() >= 12


def test_export_schema_rejects_garbage():
    assert obs_trace.validate_chrome_trace([]) != []
    assert obs_trace.validate_chrome_trace({"traceEvents": [{"ph": "Q"}]})
    assert obs_trace.validate_chrome_trace(
        {"traceEvents": [{"ph": "X", "name": "a", "pid": 1, "tid": 1,
                          "ts": -5, "dur": 1}]})
    ok = {"traceEvents": [{"ph": "X", "name": "a", "pid": 1, "tid": 1,
                           "ts": 0.0, "dur": 1.0}]}
    assert obs_trace.validate_chrome_trace(ok) == []


def test_export_to_file_and_open_span_closure(tmp_path):
    path = str(tmp_path / "t.json")
    with obs_trace.tracing(out=path):
        obs_trace._begin("left_open", "t")  # deliberately not ended
    doc = json.load(open(path))
    assert obs_trace.validate_chrome_trace(doc) == []
    assert any(e["name"] == "left_open" for e in _spans(doc))


def test_context_manager_scopes_and_restores():
    with obs_trace.tracing():
        obs_trace.set_context(a=1)
        with obs_trace.context(a=2, b=3):
            assert obs_trace.get_context() == {"a": 2, "b": 3}
        assert obs_trace.get_context() == {"a": 1}


def test_xprof_bracket_smoke(tmp_path):
    """tracing(xprof_dir=...) wraps the capture in profiler.profiler()
    so host spans and device kernels share an interval (degrades to a
    no-op where jax tracing is unsupported)."""
    import jax.numpy as jnp

    with obs_trace.tracing(xprof_dir=str(tmp_path)) as tr:
        with obs_trace.span("device_work"):
            (jnp.ones((8,)) * 2).block_until_ready()
    assert any(e[1] == "device_work"
               for b in tr._bufs for e in b.events)


def test_profiler_timer_emits_spans_when_armed():
    ss = profiler.StatSet()
    with ss.timer("gated"):  # timers off, tracing off: no-op
        pass
    assert "gated" not in ss.stats
    with obs_trace.tracing() as tr:
        with ss.timer("gated"):
            pass
    assert "gated" not in ss.stats  # tracing does not force accumulation
    assert [e for b in tr._bufs for e in b.events
            if e[0] == "X" and e[1] == "gated"]


# ------------------------------------------------------- profiler satellites


def test_statset_thread_safe_hammer():
    """StatSet.get dict insertion + Stat.add under 8 hammering threads:
    exact counts, no lost updates (the serving pool / checkpoint writer
    race the satellite fixes)."""
    ss = profiler.StatSet(keep_samples=16)
    N_THREADS, N_ADDS = 8, 2000
    names = [f"t{i}" for i in range(4)]

    def hammer(seed):
        for i in range(N_ADDS):
            ss.get(names[(seed + i) % len(names)]).add(0.001)

    threads = [threading.Thread(target=hammer, args=(s,))
               for s in range(N_THREADS)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = sum(s.count for s in ss.stats.values())
    assert total == N_THREADS * N_ADDS, total
    for s in ss.stats.values():
        assert abs(s.total - s.count * 0.001) < 1e-6


def test_stat_median_exported():
    ss = profiler.StatSet(keep_samples=5)
    for v in (0.01, 0.03, 0.5):
        ss.get("k").add(v)
    d = ss.as_dict()["k"]
    assert d["median"] == 0.03
    table = ss.print_all_status()
    assert "med(ms)" in table
    # retention off: no median key (zero-overhead default unchanged)
    ss2 = profiler.StatSet()
    ss2.get("k").add(0.1)
    assert "median" not in ss2.as_dict()["k"]
    assert "med(ms)" not in ss2.print_all_status()


# ----------------------------------------------------------------- metrics --


def test_registry_prometheus_compliance_and_roundtrip():
    reg = registry()
    reg.counter_inc("pt_t_req_total", help="reqs",
                    labels={"model": 'we"ird\\mo\ndel'})
    reg.counter_inc("pt_t_req_total", by=2, labels={"model": "plain"})
    reg.gauge("pt_t_depth", lambda: 3, help="queue depth")
    h = reg.histogram("pt_t_lat", buckets=(0.1, 1.0), help="latency")
    h.observe(0.05)
    h.observe(5.0)
    text = reg.render()
    # HELP/TYPE exactly once per family
    for fam in ("pt_t_req_total", "pt_t_depth", "pt_t_lat"):
        assert text.count(f"# TYPE {fam} ") == 1, fam
    # quantile convenience gauges are typed families of their own
    assert "# TYPE pt_t_lat_p99 gauge" in text
    fams = promparse.parse_text(text)  # strict parse of the whole render
    assert fams["pt_t_req_total"].type == "counter"
    # escaped label value round-trips exactly
    assert fams["pt_t_req_total"].value({"model": 'we"ird\\mo\ndel'}) == 1
    assert fams["pt_t_req_total"].value({"model": "plain"}) == 2
    assert fams["pt_t_depth"].value() == 3
    hist = fams["pt_t_lat"]
    assert hist.type == "histogram"
    buckets = {lb["le"]: v for n, lb, v in hist.samples
               if n == "pt_t_lat_bucket"}
    assert buckets == {"0.1": 1, "1": 1, "+Inf": 2}


def test_registry_counter_declared_before_first_inc():
    reg = registry()
    reg.declare_counter("pt_t_pre_total", help="pre-registered")
    fams = promparse.parse_text(reg.render())
    assert fams["pt_t_pre_total"].value() == 0.0
    reg.counter_inc("pt_t_pre_total")
    assert reg.counter_value("pt_t_pre_total") == 1.0


def test_registry_dead_gauge_skipped():
    reg = registry()
    reg.gauge("pt_t_dead", lambda: None, help="dead weakref source")
    text = reg.render()
    assert "pt_t_dead " not in text  # series skipped, no NaN noise


def test_fault_counts_in_unified_render():
    from paddle_tpu.resilience import faults

    faults.arm("executor.step", hit=1)
    with pytest.raises(faults.InjectedFault):
        faults.fire("executor.step")
    try:
        fams = promparse.parse_text(registry().render())
        assert fams["pt_fault_hits_total"].value(
            {"point": "executor.step"}) == 1
        assert fams["pt_fault_fired_total"].value(
            {"point": "executor.step"}) == 1
    finally:
        faults.disarm()


def test_promparse_rejects_malformed():
    for bad in ("metric_without_value",
                'm{le="0.1} 1',          # unterminated label value
                'm{le=0.1} 1',           # unquoted label value
                "m 1 2 3",               # trailing garbage
                "# TYPE m wrongtype",
                "9metric 1"):
        with pytest.raises(promparse.ParseError):
            promparse.parse_text(bad)
    # conflicting duplicate TYPE for one family is the renderer bug the
    # smoke test exists to catch
    with pytest.raises(promparse.ParseError):
        promparse.parse_text("# TYPE m counter\n# TYPE m gauge\nm 1")
    fams = promparse.parse_text(
        '# TYPE m counter\nm{a="x"} 2\nm{a="y"} +Inf\n')
    assert fams["m"].value({"a": "x"}) == 2
    assert fams["m"].value({"a": "y"}) == float("inf")


# ------------------------------------------------ serving smoke (tier-1 CI) -


def _dense_model_dir(tmp_path):
    pt.reset()
    pt.default_startup_program().random_seed = 3
    x = pt.layers.data("x", shape=[4])
    pred = pt.layers.fc(x, size=2)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    d = str(tmp_path / "dense")
    pt.io.save_inference_model(d, ["x"], [pred])
    return d


def test_metrics_smoke_scrape_parses_and_counters_monotonic(tmp_path):
    """The CI smoke test the ISSUE names: scrape /metrics, assert every
    exported family parses and every counter is monotonic across two
    scrapes — with traffic in between. Also: the serving counters are
    pre-registered, so the FIRST scrape (zero requests served) already
    exposes the full family surface at 0."""
    from paddle_tpu.serving import BucketPolicy, ModelRegistry, make_server

    d = _dense_model_dir(tmp_path)
    reg = ModelRegistry()
    reg.add("default", model_dir=d, policy=BucketPolicy(max_batch_size=8),
            timeout_ms=20000.0)
    srv = make_server(reg)
    srv.serve_background()
    try:
        url = f"http://127.0.0.1:{srv.port}"

        def scrape():
            with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
                return promparse.parse_text(r.read().decode())

        first = scrape()
        for fam in ("ptserving_requests_total", "ptserving_shed_total",
                    "ptserving_deadline_exceeded_total",
                    "ptserving_circuit_open_total",
                    "ptserving_compile_cache_hits_total",
                    "ptserving_compile_cache_misses_total",
                    "ptserving_dispatches_total",
                    "ptserving_syncs_total"):
            assert first[fam].value() == 0.0, fam  # pre-registered
        assert first["ptserving_queue_depth"].type == "gauge"
        # the unified surface: trace + engine families in ONE scrape
        assert "pt_trace_dropped_total" in first

        body = json.dumps(
            {"inputs": {"x": [[0.0, 1.0, 2.0, 3.0]]}}).encode()
        for _ in range(3):
            urllib.request.urlopen(urllib.request.Request(
                url + "/predict", data=body,
                headers={"Content-Type": "application/json"}),
                timeout=60).read()
        second = scrape()
        assert second["ptserving_requests_total"].value() >= 3
        for name, fam in first.items():
            if fam.type != "counter":
                continue
            after = second.get(name)
            assert after is not None, f"counter family {name} vanished"
            for sname, labels, v in fam.samples:
                later = [v2 for n2, lb2, v2 in after.samples
                         if n2 == sname and lb2 == labels]
                assert later and later[0] >= v, (
                    f"counter {sname}{labels} went {v} -> {later}")
    finally:
        srv.shutdown()
        reg.stop()
        srv.server_close()


# ------------------------------------------- correlation: generation spans --

V, E, H = 12, 8, 16
BOS, EOS = 0, 1
K, T = 3, 6


def _gen_model_dir(tmp_path):
    """Tiny GRU-ish decoder (the test_gen_serving model) saved with the
    generation meta sidecar."""
    pt.reset()
    pt.default_startup_program().random_seed = 3
    h0 = pt.layers.data("h0", shape=[-1, H], append_batch_size=False)
    gen = pt.layers.BeamSearchDecoder(beam_size=K, max_len=T,
                                      bos_id=BOS, eos_id=EOS)
    with gen.step():
        prev = gen.prev_ids()
        h_prev = gen.memory(init=h0)
        emb = pt.layers.embedding(prev, size=[V, E], param_attr="o_emb")
        h = pt.layers.fc(
            pt.layers.concat([emb, h_prev], axis=1), size=H, act="tanh",
            param_attr="o_w", bias_attr=pt.ParamAttr(name="o_b"))
        gen.update_memory(h_prev, h)
        gen.output_logits(pt.layers.fc(
            h, size=V, param_attr="o_wo",
            bias_attr=pt.ParamAttr(name="o_bo")))
    ids, scores, lengths = gen()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    d = str(tmp_path / "gen")
    pt.io.save_inference_model(d, ["h0"], [ids, scores, lengths])
    return d


def test_request_id_on_every_span_of_a_generation_request(tmp_path):
    """queue→admit→pool-step→stream: every request-scoped span/instant
    of a served generation request carries its request_id, across the
    client thread and the scheduler worker thread."""
    from paddle_tpu.serving import BucketPolicy, ServingEngine

    d = _gen_model_dir(tmp_path)
    eng = ServingEngine(d, policy=BucketPolicy(max_batch_size=8),
                        model_name="g")
    sched = eng.scheduler(max_slots=2)
    rng = np.random.RandomState(5)
    with obs_trace.tracing() as tr:
        handle = sched.submit({"h0": rng.randn(1, H).astype(np.float32)})
        out = handle.result(timeout=60)
    assert out["ids"].shape[0] == 1
    rid = handle.request_id
    assert rid and rid.startswith("gen-")
    doc = tr.to_chrome()
    assert obs_trace.validate_chrome_trace(doc) == []
    evs = _spans(doc) + _instants(doc)
    gen_evs = {e["name"]: e for e in evs if e.get("cat") == "gen"
               and e["name"] != "gen.pool_step"}
    # the full request-scoped chain, each event tagged with THE id
    for name in ("gen.enqueue", "gen.prefix", "gen.admit",
                 "gen.first_token", "gen.retire"):
        assert name in gen_evs, (name, sorted(gen_evs))
        assert gen_evs[name]["args"]["request_id"] == rid, name
    # enqueue happened on the client thread, admission on the worker
    assert gen_evs["gen.enqueue"]["tid"] != gen_evs["gen.admit"]["tid"]
    # the shared pool-step spans exist and carry step/active args
    steps = [e for e in _spans(doc) if e["name"] == "gen.pool_step"]
    assert steps and all("active" in e["args"] for e in steps)
    sched.stop()


# ------------------------------------------------- the mixed-run acceptance -


def test_mixed_run_single_trace_four_threads(tmp_path):
    """ISSUE 8 acceptance: one armed capture over a training pass AND
    served generation requests exports ONE schema-valid Chrome trace
    with spans on >= 4 distinct threads, at least one request whose
    queue→admit→first-token chain shares a request_id, and at least one
    step whose prefetch→enqueue(forwardBackward)→hostSync→checkpoint
    spans are linked by batch/step correlation ids."""
    from paddle_tpu.serving import BucketPolicy, ModelRegistry, make_server

    gen_dir = _gen_model_dir(tmp_path)
    out_path = str(tmp_path / "mixed.trace.json")

    reg = ModelRegistry()
    reg.add("gen", model_dir=gen_dir,
            policy=BucketPolicy(max_batch_size=8),
            scheduler_kw={"max_slots": 2}, timeout_ms=60000.0)
    srv = make_server(reg)
    srv.serve_background()

    # training side: mnist-ish mlp with background checkpointing and the
    # device prefetcher (its producer thread is one of the >= 4)
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 11
    with pt.program_guard(prog, startup):
        x = pt.layers.data("x", shape=[16])
        y = pt.layers.data("y", shape=[1])
        hmid = pt.layers.fc(x, size=32, act="tanh")
        pred = pt.layers.fc(hmid, size=1)
        loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
        pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    cc = pt.CheckpointConfig(str(tmp_path / "ck"), epoch_interval=0,
                             step_interval=4)
    trainer = pt.Trainer(loss, main_program=prog, startup_program=startup,
                         checkpoint_config=cc)
    rng = np.random.RandomState(0)
    batches = [{"x": rng.randn(8, 16).astype(np.float32),
                "y": rng.randn(8, 1).astype(np.float32)}
               for _ in range(10)]

    def reader():
        yield from batches

    url = f"http://127.0.0.1:{srv.port}"
    try:
        with obs_trace.tracing(out=out_path):
            trainer.train(reader, num_passes=1, log_interval=4,
                          prefetch_to_device=2)
            h0 = rng.randn(2, H).astype(np.float32)
            body = json.dumps({"inputs": {"h0": h0.tolist()},
                               "timeout_ms": 60000}).encode()
            with urllib.request.urlopen(urllib.request.Request(
                    url + "/generate/gen", data=body,
                    headers={"Content-Type": "application/json"}),
                    timeout=60) as r:
                assert json.load(r)["outputs"]["ids"]
    finally:
        srv.shutdown()
        reg.stop()
        srv.server_close()

    doc = json.load(open(out_path))
    assert obs_trace.validate_chrome_trace(doc) == []
    _assert_nested_per_thread(doc)
    spans = _spans(doc)
    # >= 4 distinct threads hold spans: trainer main, prefetch producer,
    # checkpoint writer, scheduler worker, HTTP handler(s)
    assert len({e["tid"] for e in spans}) >= 4, (
        sorted({(e["tid"], e["name"]) for e in spans}))

    # (a) one request's queue→admit→first-token chain, one id
    evs = spans + _instants(doc)
    rids = {e["args"]["request_id"] for e in evs
            if e["name"] == "gen.enqueue"}
    assert rids
    rid = rids.pop()
    chain = {e["name"] for e in evs
             if e.get("args", {}).get("request_id") == rid}
    assert {"gen.enqueue", "gen.admit", "gen.first_token"} <= chain, chain

    # (b) one training step's prefetch→enqueue→sync spans linked by the
    # batch/step correlation ids, across >= 2 threads
    pf = {e["args"]["batch"]: e for e in spans
          if e["name"] == "prefetch.batch"}
    fb = {e["args"]["batch"]: e for e in spans
          if e["name"] == "forwardBackward"}
    shared = set(pf) & set(fb)
    assert shared, (sorted(pf), sorted(fb))
    b = min(shared)
    assert pf[b]["tid"] != fb[b]["tid"]  # producer thread vs trainer
    syncs = [e for e in spans if e["name"] == "hostSync"
             and "step" in e.get("args", {})]
    assert syncs
    # (c) the background checkpoint commit carries the step id on the
    # writer thread, linked to the snapshot on the trainer thread
    commits = [e for e in spans if e["name"] == "checkpointCommit"]
    snaps = [e for e in spans if e["name"] == "checkpointSnapshot"]
    assert commits and snaps
    assert commits[0]["tid"] != snaps[0]["tid"]
    assert commits[0]["args"]["step"] == snaps[0]["args"]["step"]


# ------------------------------------------------------------ trainer stats -


def test_trainer_stats_line_and_registry_gauges(caplog):
    import logging

    pt.reset()
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    trainer = pt.Trainer(cost=loss)
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(5):
            yield {"x": rng.randn(4, 4).astype(np.float32),
                   "y": rng.randn(4, 1).astype(np.float32)}

    saved = pt.FLAGS.stats_period
    pt.FLAGS.stats_period = 2
    try:
        with caplog.at_level(logging.INFO, logger="paddle_tpu.stats"):
            trainer.train(reader, num_passes=1)
    finally:
        pt.FLAGS.stats_period = saved
    lines = [r.message for r in caplog.records
             if r.name == "paddle_tpu.stats"]
    assert any("step=4" in ln and "dispatches=" in ln for ln in lines), lines
    fams = promparse.parse_text(registry().render())
    assert fams["pt_trainer_step"].value() == 5
    assert fams["pt_trainer_dispatches_total"].value() == 5
    assert fams["pt_ckpt_commits_total"].value() == 0
    assert fams["pt_guard_rollbacks_total"].value() == 0


def test_dead_trainer_gauges_disappear():
    pt.reset()
    x = pt.layers.data("x", shape=[4])
    loss = pt.layers.mean(pt.layers.fc(x, size=1))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    trainer = pt.Trainer(cost=loss)
    assert "pt_trainer_step 0" in registry().render()
    del trainer
    import gc

    gc.collect()
    assert "pt_trainer_step" not in registry().render()


# ------------------------------------------------------------------- CLI ----


def test_cli_stats_file(tmp_path, capsys):
    from paddle_tpu import cli
    from paddle_tpu.tune import overrides as tune_overrides

    registry().counter_inc("pt_demo_total", help="demo",
                           labels={"kind": "a"})
    # exercise the tuned-coverage summary: one analytic consult and one
    # exact-table hit land in pt_tune_consults_total
    tune_overrides.lookup("bahdanau_attention",
                          {"B": 16, "Sp": 16, "A": 128, "C": 128},
                          "float32")
    tune_overrides.table().put(
        "bahdanau_attention", {"B": 16, "Sp": 16, "A": 128, "C": 128},
        "float32", {"bblk": 8})
    tune_overrides.lookup("bahdanau_attention",
                          {"B": 16, "Sp": 16, "A": 128, "C": 128},
                          "float32")
    p = tmp_path / "m.prom"
    p.write_text(registry().render())
    assert cli.main(["stats", "--file", str(p)]) == 0
    out = capsys.readouterr().out
    assert "pt_demo_total" in out and "families parsed OK" in out
    assert "tuned coverage: 50% of 2 kernel consults" in out


def test_cli_stats_rejects_malformed_file(tmp_path):
    from paddle_tpu import cli

    p = tmp_path / "bad.prom"
    p.write_text("this is { not an exposition\n")
    with pytest.raises(SystemExit, match="did not parse"):
        cli.main(["stats", "--file", str(p)])


# ------------------------------------------------ lint: disarmed = zero work


_TRACE_HOT_FNS = {"set_context", "span", "instant", "counter",
                  "_begin", "_end", "get_context", "new_request_id"}

# (module, function) pairs whose bodies are per-step/per-token hot
# paths: EVERY trace hook call inside them must sit under an
# `if <alias>._armed` guard so the disarmed path does zero allocations
# (the kwargs dict of an unguarded span()/set_context() call is real
# work the disarmed branch must not pay).
_HOT_PATHS = [
    ("paddle_tpu.trainer", "_step_pass"),
    ("paddle_tpu.trainer", "_scan_pass"),
    ("paddle_tpu.trainer", "_scan_one"),
    ("paddle_tpu.data.feeder", "produce"),
    ("paddle_tpu.serving.scheduler", "_step_once"),
    # serving v3 hot loops: the speculative round (per-round, streams
    # up to draft_k tokens per slot) and the prefix-cache lookup
    # (per-admission)
    ("paddle_tpu.serving.scheduler", "_spec_round"),
    ("paddle_tpu.serving.prefix_cache", "get"),
]


def _find_funcs(tree, name):
    return [n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n.name == name]


def _armed_guard_ranges(fn_node):
    """Line ranges of if-blocks whose test reads *._armed (or a local
    `armed` bool derived from it)."""
    ranges = []
    for node in ast.walk(fn_node):
        if isinstance(node, ast.If) and "_armed" in ast.dump(node.test) \
                or (isinstance(node, ast.If)
                    and isinstance(node.test, ast.Name)
                    and node.test.id == "armed"):
            end = max(getattr(n, "end_lineno", node.lineno)
                      for n in ast.walk(node))
            ranges.append((node.lineno, end))
    return ranges


def test_disarmed_tracing_zero_alloc_lint():
    """Extend the test_scan_trainer AST-lint pattern to tracing: on the
    hot loops, trace-hook calls (which build kwargs dicts / mutate
    context) may only appear inside `if ..._armed` branches."""
    import importlib

    for mod_name, fn_name in _HOT_PATHS:
        mod = importlib.import_module(mod_name)
        with open(mod.__file__) as f:
            tree = ast.parse(f.read())
        fns = _find_funcs(tree, fn_name)
        assert fns, f"{mod_name}.{fn_name} not found (lint is stale)"
        for fn in fns:
            guards = _armed_guard_ranges(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                f_ = node.func
                if not (isinstance(f_, ast.Attribute)
                        and f_.attr in _TRACE_HOT_FNS
                        and isinstance(f_.value, ast.Name)
                        and "trace" in f_.value.id):
                    continue
                line = node.lineno
                assert any(lo <= line <= hi for lo, hi in guards), (
                    f"{mod_name}.{fn_name}:{line} calls trace hook "
                    f"{f_.attr}() outside an `if ..._armed` guard — "
                    "that work runs on the DISARMED step path")
