"""Per-layer placement / tensor-parallel FC demo (ParallelNeuralNetwork
parity).

Reference: ParallelNeuralNetwork dispatches layers to devices from a
per-layer `device` attr (gserver/gradientmachines/ParallelNeuralNetwork.h:34,
proto/ModelConfig.proto:399). TPU-native: a Variable's `.sharding`
PartitionSpec places that layer's weight over a mesh axis; GSPMD inserts
the collectives. Here a wide FC pair runs Megatron-style over `mp`
(column-parallel W1, row-parallel W2 — the activation stays sharded
between them and one psum materializes after W2), trained on the 8-device
CPU mesh, asserted equal to the replicated run.
"""

import numpy as np
import pytest

import jax
from jax.sharding import PartitionSpec

import paddle_tpu as pt
from paddle_tpu import parallel as pp


@pytest.fixture
def mesh42():
    assert len(jax.devices()) == 8, "conftest must force 8 virtual devices"
    return pp.make_mesh((4, 2), ("dp", "mp"))


def _build(shard_over=None):
    """MLP with a wide hidden layer; shard_over="mp" marks W1
    column-parallel and W2 row-parallel via Variable.sharding."""
    x = pt.layers.data("x", shape=[16])
    y = pt.layers.data("y", shape=[1])
    h = pt.layers.fc(x, size=64, act="relu",
                     param_attr=pt.ParamAttr(name="w1"), bias_attr=False)
    pred = pt.layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w2"),
                        bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    if shard_over:
        gb = pt.default_main_program().global_block()
        # column-parallel: [in, hidden] split on the hidden (output) dim
        gb.var("w1").sharding = PartitionSpec(None, shard_over)
        # row-parallel: [hidden, out] split on the hidden (input) dim;
        # GSPMD emits the mp psum after this matmul
        gb.var("w2").sharding = PartitionSpec(shard_over, None)
    return loss


def _train(executor_factory, shard_over, steps=4):
    pt.reset()
    loss_var = _build(shard_over)
    prog = pt.default_main_program()
    prog.random_seed = 9
    pt.default_startup_program().random_seed = 9
    exe = executor_factory()
    pt.Executor().run(pt.default_startup_program())
    rng = np.random.RandomState(1)
    xv = rng.randn(16, 16).astype(np.float32)
    yv = rng.randn(16, 1).astype(np.float32)
    losses = []
    for _ in range(steps):
        (l,) = exe.run(prog, feed={"x": xv, "y": yv}, fetch_list=[loss_var])
        losses.append(float(l))
    w1 = np.asarray(pt.global_scope().get("w1"))
    w2 = np.asarray(pt.global_scope().get("w2"))
    return losses, w1, w2


def test_tensor_parallel_fc_matches_replicated(mesh42):
    ls_rep, w1_rep, w2_rep = _train(pt.Executor, shard_over=None)
    ls_tp, w1_tp, w2_tp = _train(
        lambda: pp.ParallelExecutor(mesh42), shard_over="mp"
    )
    np.testing.assert_allclose(ls_tp, ls_rep, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(w1_tp, w1_rep, rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(w2_tp, w2_rep, rtol=1e-4, atol=1e-6)


def test_sharding_is_physically_applied(mesh42):
    """The mp-sharded weight must actually live split across mesh devices
    (not just numerically agree): check the committed sharding on device."""
    pt.reset()
    loss_var = _build(shard_over="mp")
    prog = pt.default_main_program()
    exe = pp.ParallelExecutor(mesh42)
    pt.Executor().run(pt.default_startup_program())
    rng = np.random.RandomState(1)
    feed = {"x": rng.randn(8, 16).astype(np.float32),
            "y": rng.randn(8, 1).astype(np.float32)}
    exe.run(prog, feed=feed, fetch_list=[loss_var])
    w1_dev = pt.global_scope().get("w1")
    spec = w1_dev.sharding.spec
    assert tuple(spec) == (None, "mp"), spec
    # each device holds a [16, 32] column slice of the [16, 64] weight
    shard_shapes = {s.data.shape for s in w1_dev.addressable_shards}
    assert shard_shapes == {(16, 32)}, shard_shapes
