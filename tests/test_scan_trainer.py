"""Windowed training loop (ISSUE 6): K steps fused into one jitted
lax.scan dispatch.

The load-bearing claim mirrors the PR 5 pipeline's: fusing changes HOW
MANY programs the host dispatches, never WHAT the device computes. The
fixed-seed A/B demands bit-identical final parameters and identical pass
metrics between the per-step loop and the scan loop — including a ragged
final window and a StepGuard-armed run — while the dispatch counter must
drop by ~K. Window-edge semantics (guard detection lag, checkpoint
quantization, SIGTERM finishing the in-flight window) get their own
cases, and the stray-host-sync lint extends to the window path's modules.
"""

import json
import os
import signal

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu.resilience import PreemptedError, faults
from paddle_tpu.resilience.guard import StepGuard

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------ model + data helpers

def _mnist_mlp():
    img = pt.layers.data("img", shape=[784])
    label = pt.layers.data("label", shape=[1], dtype=np.int32)
    h = pt.layers.fc(img, size=64, act="tanh")
    logits = pt.layers.fc(h, size=10)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    acc = pt.layers.accuracy(logits, label)
    return loss, acc


def _mnist_reader(n_batches=10, batch=16, seed=0):
    rng = np.random.RandomState(seed)
    data = [
        {"img": rng.randn(batch, 784).astype(np.float32),
         "label": rng.randint(0, 10, (batch, 1)).astype(np.int32)}
        for _ in range(n_batches)
    ]

    def reader():
        yield from data
    return reader


def _train_once(log_interval, scan_window, reader, num_passes=2,
                step_guard=None, checkpoint_dir=None, event_handler=None,
                arm=None, step_interval=2):
    pt.reset()
    if arm is not None:
        arm()  # pt.reset() disarms the fault registry — re-arm after it
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 1234
    with pt.program_guard(prog, startup):
        loss, acc = _mnist_mlp()
        pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    cc = (pt.CheckpointConfig(checkpoint_dir, epoch_interval=0,
                              step_interval=step_interval,
                              max_num_checkpoints=100)
          if checkpoint_dir else None)
    trainer = pt.Trainer(loss, main_program=prog, startup_program=startup,
                         checkpoint_config=cc, step_guard=step_guard)
    metrics = trainer.train(
        reader, num_passes=num_passes, fetch_metrics={"acc": acc},
        event_handler=event_handler, log_interval=log_interval,
        scan_window=scan_window)
    params = {p.name: np.asarray(pt.global_scope().get(p.name)).copy()
              for p in prog.parameters()}
    return metrics, params, trainer


# ------------------------------------------------- the acceptance A/B


@pytest.mark.parametrize("k", [1, 4])
def test_scan_vs_step_bitidentical_params_and_metrics(k):
    """Fixed-seed MNIST-mlp, 10 batches (K=4 ⇒ windows of 4,4,2 — the
    ragged tail is part of the A/B): the scan loop must produce the SAME
    run as the per-step-sync loop, and for K>1 it must issue strictly
    fewer host dispatches — the whole point of fusing."""
    reader = _mnist_reader()
    m_step, p_step, t_step = _train_once(1, None, reader)
    m_scan, p_scan, t_scan = _train_once(16, k, reader)

    assert sorted(p_step) == sorted(p_scan)
    for name in p_step:
        np.testing.assert_array_equal(p_step[name], p_scan[name])
    assert m_step == m_scan, (m_step, m_scan)
    assert np.isfinite(m_scan["cost"]) and "acc" in m_scan
    assert t_scan.host_sync_count < t_step.host_sync_count
    assert t_scan.host_dispatch_count <= t_step.host_dispatch_count
    if k > 1:
        # 10 batches/pass, 2 passes: 20 per-step dispatches vs 6 windows
        assert t_scan.host_dispatch_count < t_step.host_dispatch_count, (
            t_scan.host_dispatch_count, t_step.host_dispatch_count)
        assert t_scan.host_dispatch_count == 2 * 3  # 4+4+2 per pass


def test_scan_vs_async_fewer_dispatches():
    """PR 5's async loop HIDES the per-step dispatch; the window loop
    REMOVES it. Same cadence, same params — fewer dispatches."""
    reader = _mnist_reader(n_batches=8)
    m_async, p_async, t_async = _train_once(16, None, reader)
    m_scan, p_scan, t_scan = _train_once(16, 4, reader)
    for name in p_async:
        np.testing.assert_array_equal(p_async[name], p_scan[name])
    assert m_async == m_scan
    assert t_scan.host_dispatch_count < t_async.host_dispatch_count, (
        t_scan.host_dispatch_count, t_async.host_dispatch_count)
    assert t_scan.host_sync_count <= t_async.host_sync_count


def test_scan_guard_armed_ab_still_bitidentical(tmp_path):
    """A StepGuard-armed run (skip_nonfinite accumulator variant, clean
    data) must also be bit-identical across step/scan — the guard only
    changes what happens on NON-finite steps."""
    reader = _mnist_reader(n_batches=8)
    d1, d2 = str(tmp_path / "a"), str(tmp_path / "b")
    m_step, p_step, _ = _train_once(
        1, None, reader, step_guard=StepGuard(), checkpoint_dir=d1)
    m_scan, p_scan, _ = _train_once(
        4, 4, reader, step_guard=StepGuard(), checkpoint_dir=d2)
    for name in p_step:
        np.testing.assert_array_equal(p_step[name], p_scan[name])
    assert m_step == m_scan


# ------------------------------------------------- window-edge semantics


@pytest.mark.chaos
def test_scan_guard_catches_nan_within_one_window(tmp_path):
    """Poison one step inside a window: the on-device non-finite counter
    rides the scan carry, so the guard learns of it at that window's
    edge sync — within ≤1 window of the injection — rolls back to a
    pre-NaN checkpoint (discarding the WHOLE window) and finishes
    finite."""
    d = str(tmp_path / "ck")
    reader = _mnist_reader(n_batches=12)
    guard = StepGuard(max_consecutive=1, cooldown_steps=2, lr_factor=0.5)
    rolled_back_at = []

    def watch(e):
        if isinstance(e, pt.EndIteration) and guard.rollbacks:
            rolled_back_at.append(e.step)

    try:
        m, params, trainer = _train_once(
            4, 4, reader, num_passes=1, step_guard=guard, checkpoint_dir=d,
            event_handler=watch, step_interval=4,
            arm=lambda: faults.arm("executor.step", hit=6, action="corrupt"))
    finally:
        faults.disarm()
    assert faults.stats()["executor.step"]["fired"] == 1
    st = guard.stats()
    # the poison landed at step 6 (window 5-8); the window-edge sync after
    # step 8 must have seen it — not the pass end at step 12
    assert st["skipped"] >= 1 and st["rollbacks"] >= 1, st
    assert rolled_back_at and min(rolled_back_at) <= 9, rolled_back_at
    assert np.isfinite(m["cost"]), m
    for name, w in params.items():
        assert np.isfinite(w).all(), name
    # rollback discarded the WHOLE window: the counter rewound to the
    # step-4 boundary checkpoint, so the 12 consumed batches land the
    # final counter at 8 — the poisoned window contributed nothing
    assert trainer.step == 8, trainer.step


@pytest.mark.chaos
def test_scan_guard_never_checkpoints_poison(tmp_path):
    """Every serial on disk after a scan-mode guard run holds finite
    parameters — the window-boundary cadence synced (and observed the
    guard) before persisting anything."""
    d = str(tmp_path / "ck")
    reader = _mnist_reader(n_batches=12)
    guard = StepGuard(max_consecutive=1, cooldown_steps=1)
    try:
        _train_once(4, 4, reader, num_passes=1, step_guard=guard,
                    checkpoint_dir=d, step_interval=4,
                    arm=lambda: faults.arm("executor.step", hit=5,
                                           action="corrupt"))
    finally:
        faults.disarm()
    latest = pio.get_latest_checkpoint_serial(d)
    assert latest >= 0
    for s in range(latest + 1):
        sd = os.path.join(d, f"checkpoint_{s}")
        if not os.path.isdir(sd):
            continue
        pt.reset_global_scope()
        pio.load_vars(sd)
        for name in pt.global_scope().keys():
            assert np.isfinite(
                np.asarray(pt.global_scope().get(name))).all(), (s, name)


def test_scan_checkpoint_cadence_quantized_to_window_boundary(tmp_path):
    """step_interval=3 with K=4: the cadence fires once per window that
    CROSSES a multiple of 3, at the window edge — every serial's step is
    a window boundary (multiple of 4), and the background commit holds
    the values of that boundary step (drained, sha-verified)."""
    d = str(tmp_path / "ck")
    reader = _mnist_reader(n_batches=8)
    snaps = {}

    def grab(e):
        if isinstance(e, pt.EndIteration) and e.step % 4 == 0:
            snaps[e.step] = {
                p.name: np.asarray(pt.global_scope().get(p.name)).copy()
                for p in pt.default_main_program().parameters()}

    _train_once(16, 4, reader, num_passes=1, checkpoint_dir=d,
                event_handler=grab, step_interval=3)
    serials = sorted(
        int(n.split("_")[1]) for n in os.listdir(d)
        if n.startswith("checkpoint_") and not n.endswith(".corrupt"))
    assert serials, "cadence never fired"
    steps_seen = []
    for serial in serials:
        sd = os.path.join(d, f"checkpoint_{serial}")
        pio.verify_checkpoint(sd)  # background write fully drained
        with open(os.path.join(sd, pio.META_FILE)) as f:
            step = json.load(f)["trainer_args"]["step"]
        steps_seen.append(step)
        assert step % 4 == 0, f"serial {serial} at non-boundary step {step}"
        if step in snaps:
            pt.reset_global_scope()
            pio.load_vars(sd)
            for name, want in snaps[step].items():
                np.testing.assert_array_equal(
                    np.asarray(pt.global_scope().get(name)), want)
    assert steps_seen == [4, 8], steps_seen  # crossings of 3 and 6


@pytest.mark.chaos
def test_scan_sigterm_mid_window_finishes_window_then_checkpoints(tmp_path):
    """SIGTERM delivered while a window is being assembled/dispatched:
    the trainer finishes the in-flight window, emergency-checkpoints at
    its boundary, and raises PreemptedError — resume re-enters at the
    window edge, losing zero completed steps."""
    d = str(tmp_path / "ck")
    reader = _mnist_reader(n_batches=12)

    def kill_mid_window(e):
        # BeginIteration for batch 5 fires during window 2's assembly —
        # before its dispatch completes
        if isinstance(e, pt.BeginIteration) and e.batch_id == 5:
            os.kill(os.getpid(), signal.SIGTERM)

    pt.reset()
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 7
    with pt.program_guard(prog, startup):
        loss, acc = _mnist_mlp()
        pt.optimizer.Adam(learning_rate=1e-3).minimize(loss)
    cc = pt.CheckpointConfig(d, epoch_interval=0, step_interval=0)
    trainer = pt.Trainer(loss, main_program=prog, startup_program=startup,
                         checkpoint_config=cc)
    with pytest.raises(PreemptedError):
        trainer.train(reader, num_passes=2, event_handler=kill_mid_window,
                      log_interval=16, scan_window=4)
    # the emergency save landed at the boundary of the window that was
    # in flight when the signal arrived (batches 4-7 → step 8)
    serial = pio.get_latest_checkpoint_serial(d)
    assert serial >= 0
    sd = os.path.join(d, f"checkpoint_{serial}")
    pio.verify_checkpoint(sd)  # writer drained before PreemptedError
    with open(os.path.join(sd, pio.META_FILE)) as f:
        args = json.load(f)["trainer_args"]
    assert args["step"] == 8 and args["mid_pass"] and args["batch_id"] == 7
    pt.reset_global_scope()
    t2 = pt.Trainer(loss, main_program=prog, startup_program=startup,
                    checkpoint_config=cc)
    t2.init()
    assert t2.step == 8 and t2._resume_batch == 8


# ------------------------------------------------- window assembly


def test_prefetcher_window_grouping_and_ragged_flush():
    """DevicePrefetcher(window=4): consecutive same-signature batches
    stack to FeedWindow objects; a signature change flushes the partial
    window so no compiled window ever mixes shapes; the tail flushes at
    pass end."""
    from paddle_tpu.data.feeder import DevicePrefetcher, FeedWindow

    def reader():
        for _ in range(5):
            yield {"x": np.ones((2, 3), np.float32)}
        for _ in range(3):
            yield {"x": np.ones((4, 3), np.float32)}  # signature change

    wins = list(DevicePrefetcher(reader, window=4))
    assert all(isinstance(w, FeedWindow) for w in wins)
    assert [w.k for w in wins] == [4, 1, 3]
    assert wins[0].feed["x"].shape == (4, 2, 3)
    assert wins[2].feed["x"].shape == (3, 4, 3)
    # slice() keeps the leading window axis (a window of 1)
    assert wins[0].slice(2)["x"].shape == (1, 2, 3)


def test_run_window_rejects_empty_feed():
    import paddle_tpu.core.executor as ex

    with pytest.raises(ValueError, match="feed"):
        ex.Executor().run_window(pt.Program(), feed={}, fetch_list=[])


def test_parallel_executor_falls_back_loudly(caplog):
    """scan_window on a mesh executor must fall back to the per-step
    loop with a warning, not silently no-op or crash."""
    import logging

    from paddle_tpu.parallel.data_parallel import ParallelExecutor

    assert ParallelExecutor.scan_window_supported is False
    pt.reset()
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    trainer = pt.Trainer(loss, executor=ParallelExecutor())
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(2):
            yield {"x": rng.randn(8, 4).astype(np.float32),
                   "y": rng.randn(8, 1).astype(np.float32)}

    with caplog.at_level(logging.WARNING, logger="paddle_tpu.trainer"):
        m = trainer.train(reader, num_passes=1, scan_window=4)
    assert np.isfinite(m["cost"])
    assert any("scan_window" in r.message for r in caplog.records)


# ------------------------------------------------- lint: no stray syncs


def test_no_stray_host_syncs_in_window_modules():
    """The window path (executor run_window/_build_window, feeder
    stacking) must never read a value back to host: a single stray
    float(np.asarray(...)) / jax.device_get would re-fence every window.
    trainer.py's own lint (test_async_trainer) covers the trainer side;
    this extends the ban to the modules the window path grew into."""
    import paddle_tpu.core.executor as ex_mod
    import paddle_tpu.data.feeder as fd_mod

    for mod, allowed in ((ex_mod, ("device_get",)), (fd_mod, ())):
        with open(mod.__file__) as f:
            src = f.read()
        for i, line in enumerate(src.splitlines(), 1):
            code = line.split("#", 1)[0]
            assert "float(np.asarray" not in code, (mod.__name__, i, line)
            if "device_get" not in allowed:
                assert "jax.device_get" not in code, (mod.__name__, i, line)
