"""Rematerialization (memory_optimize) tests.

Reference analogue: fluid memory_optimization_transpiler tests — the
optimized program must train to the same result; here remat must leave
gradients bit-comparable while trading activation memory for recompute.
"""

import numpy as np
import pytest

import paddle_tpu as pt


def _build():
    x = pt.layers.data("x", shape=[8])
    label = pt.layers.data("label", shape=[1], dtype=np.int32)
    h = pt.layers.fc(x, size=16, act="relu")
    h = pt.layers.fc(h, size=16, act="tanh")
    logits = pt.layers.fc(h, size=3)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    return loss


def _train(policy, steps=4):
    pt.reset()
    prog = pt.default_main_program()
    loss = _build()
    prog.random_seed = 11
    pt.default_startup_program().random_seed = 11
    if policy:
        pt.memory_optimize(prog, policy=policy)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {
        "x": rng.randn(16, 8).astype(np.float32),
        "label": rng.randint(0, 3, (16, 1)).astype(np.int32),
    }
    out = []
    for _ in range(steps):
        (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
        out.append(float(l))
    return out


@pytest.mark.parametrize("policy", ["full", "dots", "dots_no_batch"])
def test_remat_matches_baseline(policy):
    base = _train(None)
    remat = _train(policy)
    np.testing.assert_allclose(remat, base, rtol=1e-6)


def test_unknown_policy_rejected():
    with pytest.raises(ValueError, match="unknown remat policy"):
        pt.memory_optimize(pt.Program(), policy="bogus")
