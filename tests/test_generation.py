"""BeamSearchDecoder (generic generation) tests.

Reference analogues: trainer/tests/test_recurrent_machine_generation.cpp
(real beam-search generation against a fixture model) — here a small GRU
LM decodes with the generic sub-block machinery and must match a plain-
Python beam search oracle exactly.
"""

import numpy as np
import pytest

import paddle_tpu as pt


V, E, H = 12, 8, 16
BOS, EOS = 0, 1


def _build_decoder(K, T, enc_dim=H, length_normalize=False):
    h0 = pt.layers.data("h0", shape=[-1, enc_dim], append_batch_size=False)
    gen = pt.layers.BeamSearchDecoder(
        beam_size=K, max_len=T, bos_id=BOS, eos_id=EOS,
        length_normalize=length_normalize,
    )
    with gen.step():
        prev = gen.prev_ids()
        h_prev = gen.memory(init=h0)
        emb = pt.layers.embedding(prev, size=[V, E], param_attr="gen_emb")
        h = pt.layers.fc(
            pt.layers.concat([emb, h_prev], axis=1), size=H, act="tanh",
            param_attr="gen_w", bias_attr=pt.ParamAttr(name="gen_b"),
        )
        gen.update_memory(h_prev, h)
        logits = pt.layers.fc(h, size=V, param_attr="gen_wout",
                              bias_attr=pt.ParamAttr(name="gen_bout"))
        gen.output_logits(logits)
    return gen(), h0


def _np_params(scope):
    g = lambda n: np.asarray(scope.get(n))
    return g("gen_emb"), g("gen_w"), g("gen_b"), g("gen_wout"), g("gen_bout")


def _np_beam(h0, K, T, params):
    """Plain-python beam search oracle over the same tiny GRU-ish LM."""
    emb_w, w, b, wout, bout = params

    def step(tok, h):
        x = np.concatenate([emb_w[tok], h])
        h2 = np.tanh(x @ w + b)
        logits = h2 @ wout + bout
        lp = logits - (np.log(np.exp(logits - logits.max()).sum()) + logits.max())
        return h2, lp

    beams = [(0.0, [BOS], h0, False)]
    for _ in range(T):
        cand = []
        for sc, seq, h, fin in beams:
            if fin:
                cand.append((sc, seq + [EOS], h, True))
                continue
            h2, lp = step(seq[-1], h)
            for v in range(V):
                cand.append((sc + lp[v], seq + [v], h2, v == EOS))
        cand.sort(key=lambda c: -c[0])
        beams = cand[:K]
    return beams


@pytest.mark.parametrize("K", [1, 3])
def test_beam_matches_python_oracle(K):
    T = 6
    (ids, scores, lengths), h0_var = _build_decoder(K, T)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    h0 = rng.randn(2, H).astype(np.float32)
    ids_v, sc_v, len_v = exe.run(
        feed={"h0": h0}, fetch_list=[ids, scores, lengths]
    )
    params = _np_params(pt.global_scope())
    for bi in range(2):
        want = _np_beam(h0[bi], K, T, params)
        for k in range(K):
            w_sc, w_seq = want[k][0], want[k][1][1:]  # drop BOS
            np.testing.assert_allclose(sc_v[bi, k], w_sc, rtol=1e-4, atol=1e-4)
            got = list(ids_v[bi, k][: len(w_seq)])
            # compare up to the hypothesis' first EOS
            L = len_v[bi, k]
            assert got[:L] == w_seq[:L], (bi, k, got, w_seq)


def test_greedy_is_argmax_chain():
    T = 5
    (ids, scores, lengths), h0_var = _build_decoder(1, T)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(1)
    h0 = rng.randn(3, H).astype(np.float32)
    ids_v, _, _ = exe.run(feed={"h0": h0}, fetch_list=[ids, scores, lengths])
    emb_w, w, b, wout, bout = _np_params(pt.global_scope())
    for bi in range(3):
        tok, h = BOS, h0[bi]
        for t in range(T):
            x = np.concatenate([emb_w[tok], h])
            h = np.tanh(x @ w + b)
            tok = int(np.argmax(h @ wout + bout))
            assert ids_v[bi, 0, t] == tok
            if tok == EOS:
                break


def test_per_example_input_tiling():
    """Attention-style: closure tensor with leading dim B must be tiled."""
    K, T, S = 2, 4, 3
    h0 = pt.layers.data("h0", shape=[-1, H], append_batch_size=False)
    enc = pt.layers.data("enc", shape=[-1, H], append_batch_size=False)
    gen = pt.layers.BeamSearchDecoder(beam_size=K, max_len=T,
                                      bos_id=BOS, eos_id=EOS)
    with gen.step():
        prev = gen.prev_ids()
        h_prev = gen.memory(init=h0)
        enc_t = gen.per_example_input(enc)  # [B*K, H] inside
        emb = pt.layers.embedding(prev, size=[V, E])
        h = pt.layers.fc(
            pt.layers.concat([emb, h_prev, enc_t], axis=1), size=H, act="tanh")
        gen.update_memory(h_prev, h)
        gen.output_logits(pt.layers.fc(h, size=V))
    ids, scores, lengths = gen()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(2)
    ids_v, = exe.run(
        feed={"h0": rng.randn(2, H).astype(np.float32),
              "enc": rng.randn(2, H).astype(np.float32)},
        fetch_list=[ids],
    )
    assert ids_v.shape == (2, K, T)
