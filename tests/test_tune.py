"""Autotuner subsystem tests (paddle_tpu/tune/).

The contracts under test, in dependency order:
- space: every candidate a generator emits passes the SHARED legality
  predicate, and the runtime accepts exactly that config (the property
  that makes "tuner can never emit an illegal tile" true);
- cache: JSON table round-trips, atomic-ish save, corrupt-file
  recovery, schema-version gating, fingerprint stability;
- overrides: precedence (forced > env > table > analytic), the legacy
  PT_ATTN_BBLK env knob routed through the registry, fingerprint
  reactivity (the Executor jit-cache-key contract);
- harness: the CPU determinism guard (refuses to time off-TPU), and
  the measurement loop mechanics in interpret mode;
- golden numerics: a forced tuned config reproduces the analytic
  default path bit-for-bit (tile size partitions the batch; per-row
  math must be identical);
- io/serving: tuning provenance travels in meta.json and warmup warns
  on a stale table.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.flags import FLAGS
from paddle_tpu.tune import cache as tcache
from paddle_tpu.tune import harness, overrides, space


@pytest.fixture
def tmp_table(tmp_path):
    path = str(tmp_path / "tuned.json")
    overrides.set_table_path(path)
    yield path
    overrides.reset()


# ----------------------------------------------------------- space ------
BAHDANAU_GRID = [
    # (B, S, A, C, dtype)
    (8, 10, 128, 128, "float32"),
    (16, 60, 512, 512, "bfloat16"),
    (256, 60, 512, 512, "bfloat16"),
    (4, 7, 128, 256, "float32"),
    (2, 100, 128, 128, "bfloat16"),
    (24, 33, 256, 128, "float32"),
]


@pytest.mark.parametrize("B,S,A,C,dtype", BAHDANAU_GRID)
def test_bahdanau_candidates_all_legal(B, S, A, C, dtype):
    """Property: every emitted candidate passes the shared legality
    predicate AND is accepted verbatim by the runtime's _bblk when
    forced — no candidate can compile-fail on Mosaic tile rules."""
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    Sp = space.pad_s(S)
    item = 2 if dtype == "bfloat16" else 4
    params = {"B": B, "Sp": Sp, "A": A, "C": C, "dtype": dtype}
    cands = space.bahdanau_candidates(params)
    assert cands, f"no candidates at {params}"
    for cfg in cands:
        b = cfg["bblk"]
        assert space.bahdanau_blk_legal(b, B, Sp, A, C, item), cfg
        # Mosaic divisibility rules restated independently:
        assert B % b == 0
        assert b % 8 == 0 or b == B
        with overrides.forcing("bahdanau_attention", cfg):
            assert _bblk(B, Sp, A, C, item) == b
    # the analytic default is itself in the candidate set
    default = space.bahdanau_default(params)
    assert default in cands


def test_flash_and_conv_candidates_all_legal():
    for Tq, Tk in [(1024, 1024), (2048, 512), (4096, 4096), (1280, 1280)]:
        cands = space.flash_candidates({"Tq": Tq, "Tk": Tk})
        assert cands
        for cfg in cands:
            assert space.flash_block_legal(cfg["block_q"], cfg["block_k"],
                                           Tq, Tk), (cfg, Tq, Tk)
        assert space.flash_default({"Tq": Tq, "Tk": Tk}) in cands
    for n, cin, cout in [(2048, 128, 512), (1024, 256, 256),
                         (8 * 3 * 7, 128, 128)]:
        params = {"n": n, "cin": cin, "cout": cout, "dtype": "bfloat16"}
        cands = space.conv_candidates(params)
        assert cands
        for cfg in cands:
            assert space.conv_rows_legal(cfg["block_rows"], n, cin, cout, 2)
        assert space.conv_default(params) in cands


def test_rnn_space_matches_runtime_default():
    """The fused_lstm/fused_gru default mirrors lstm_supported /
    gru_supported exactly (same measured windows + hard gates)."""
    from paddle_tpu.ops.pallas_kernels import gru_supported, lstm_supported

    prev = FLAGS.fused_rnn_interpret
    FLAGS.fused_rnn_interpret = True  # neutralize the backend gate
    try:
        for B, H in [(128, 512), (128, 384), (128, 256), (64, 1280),
                     (8, 128), (12, 128)]:
            p = {"B": B, "H": H, "dtype": "bfloat16"}
            assert space._rnn_default("lstm")(p)["fused"] == lstm_supported(
                B, H, "sigmoid", "tanh", "tanh", None, itemsize=2)
            assert space._rnn_default("gru")(p)["fused"] == gru_supported(
                B, H, "sigmoid", "tanh", itemsize=2)
    finally:
        FLAGS.fused_rnn_interpret = prev


# ----------------------------------------------------------- cache ------
def test_cache_round_trip(tmp_path):
    path = str(tmp_path / "t.json")
    t = tcache.TunedTable(path, autoload=False)
    params = {"B": 16, "Sp": 16, "A": 128, "C": 128}
    t.put("bahdanau_attention", params, "float32", {"bblk": 16},
          device="cpu", meta={"median_s": 1e-3})
    fp = t.fingerprint()
    t.save()
    t2 = tcache.TunedTable(path)
    assert t2.get("bahdanau_attention", params, "float32",
                  device="cpu") == {"bblk": 16}
    assert t2.fingerprint() == fp
    # dtype and device are key dimensions: both must miss
    assert t2.get("bahdanau_attention", params, "bfloat16",
                  device="cpu") is None
    assert t2.get("bahdanau_attention", params, "float32",
                  device="tpu-v5-lite") is None
    # a 'dtype' key inside params must not change the signature
    # (space.normalize carries it; runtime lookups don't)
    assert t2.get("bahdanau_attention", dict(params, dtype="float32"),
                  "float32", device="cpu") == {"bblk": 16}


def test_cache_corrupt_file_recovery(tmp_path):
    path = str(tmp_path / "t.json")
    with open(path, "w") as f:
        f.write('{"version": 1, "entries": {truncated')
    with pytest.warns(UserWarning, match="corrupt"):
        t = tcache.TunedTable(path)
    assert len(t) == 0
    assert os.path.exists(path + ".corrupt")
    assert not os.path.exists(path)
    # the quarantined table must not break a subsequent save/load cycle
    t.put("k", {"a": 1}, "float32", {"x": 1}, device="cpu")
    t.save()
    assert tcache.TunedTable(path).get("k", {"a": 1}, "float32",
                                       device="cpu") == {"x": 1}


def test_cache_version_mismatch_ignored(tmp_path):
    path = str(tmp_path / "t.json")
    with open(path, "w") as f:
        json.dump({"version": 999, "entries": {
            "k|a=1|float32|cpu": {"config": {"x": 1}, "meta": {}}}}, f)
    with pytest.warns(UserWarning, match="schema version"):
        t = tcache.TunedTable(path)
    assert len(t) == 0  # analytic defaults apply


def test_cache_missing_file_is_empty(tmp_path):
    t = tcache.TunedTable(str(tmp_path / "absent.json"))
    assert len(t) == 0
    assert t.get("k", {"a": 1}, "float32") is None


# ------------------------------------------------------- overrides ------
def test_override_precedence(tmp_table, monkeypatch):
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    params = {"B": 16, "Sp": 16, "A": 128, "C": 128}
    # table layer
    t = overrides.table()
    t.put("bahdanau_attention", params, "float32", {"bblk": 16})
    assert _bblk(16, 16, 128, 128, 4) == 16
    # env layer beats table (legacy PT_ATTN_BBLK still honored)
    monkeypatch.setenv("PT_ATTN_BBLK", "8")
    assert _bblk(16, 16, 128, 128, 4) == 8
    # programmatic force beats env
    with overrides.forcing("bahdanau_attention", {"bblk": 16}):
        assert _bblk(16, 16, 128, 128, 4) == 16
    # flag kill-switch drops the table layer
    monkeypatch.delenv("PT_ATTN_BBLK")
    FLAGS.use_tuned_table = False
    try:
        assert _bblk(16, 16, 128, 128, 4) == 8  # analytic default
    finally:
        FLAGS.use_tuned_table = True
    assert _bblk(16, 16, 128, 128, 4) == 16


def test_flash_and_conv_consult_overrides(tmp_table):
    """flash_ops._v5e_block_sizes and fused_conv_ops._block_rows
    consult the registry before their analytic defaults."""
    import jax.numpy as jnp2

    from paddle_tpu.ops.flash_ops import _v5e_block_sizes
    from paddle_tpu.ops.fused_conv_ops import _block_rows

    # analytic defaults first
    bs = _v5e_block_sizes(1024, 1024, jnp2.bfloat16)
    assert (bs.block_q, bs.block_k) == (512, 512)
    assert _block_rows(2048, 128, 512, 2) == 1024
    # tuned table entries take over
    t = overrides.table()
    t.put("flash_attention", {"Tq": 1024, "Tk": 1024}, "bfloat16",
          {"block_q": 256, "block_k": 128})
    t.put("fused_conv", {"n": 2048, "cin": 128, "cout": 512}, "bfloat16",
          {"block_rows": 256})
    bs = _v5e_block_sizes(1024, 1024, jnp2.bfloat16)
    assert (bs.block_q, bs.block_k) == (256, 128)
    assert _block_rows(2048, 128, 512, 2) == 256
    # a stale flash entry (doesn't divide T) is ignored, not fatal
    t.put("flash_attention", {"Tq": 512, "Tk": 512}, "bfloat16",
          {"block_q": 768, "block_k": 768})
    bs = _v5e_block_sizes(512, 512, jnp2.bfloat16)
    assert (bs.block_q, bs.block_k) == (512, 512)
    # forced illegal conv block warns and disables the fused path
    with overrides.forcing("fused_conv", {"block_rows": 12}):
        with pytest.warns(UserWarning, match="fails eligibility"):
            assert _block_rows(2048, 128, 512, 2) == 0


def test_rnn_dispatch_consults_overrides(tmp_table):
    """The tuner's {"fused": bool} verdict overrides the measured
    H-window (but can never force an ineligible shape fused)."""
    from paddle_tpu.ops.pallas_kernels import gru_supported

    prev = FLAGS.fused_rnn_interpret
    FLAGS.fused_rnn_interpret = True
    try:
        # H=384 sits outside the GRU measured window -> scan by default
        assert not gru_supported(128, 384, "sigmoid", "tanh", itemsize=2)
        overrides.table().put("fused_gru", {"B": 128, "H": 384},
                              "bfloat16", {"fused": True})
        assert gru_supported(128, 384, "sigmoid", "tanh", itemsize=2)
        # hard illegality (B % 8) wins over any table verdict
        overrides.table().put("fused_gru", {"B": 12, "H": 384},
                              "bfloat16", {"fused": True})
        assert not gru_supported(12, 384, "sigmoid", "tanh", itemsize=2)
    finally:
        FLAGS.fused_rnn_interpret = prev


def test_forced_illegal_warns_and_disables(tmp_table):
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    with overrides.forcing("bahdanau_attention", {"bblk": 3}):
        with pytest.warns(UserWarning, match="fails eligibility"):
            assert _bblk(16, 16, 128, 128, 4) == 0


def test_stale_table_entry_falls_back_to_analytic(tmp_table):
    """A shipped table must never break a model: an entry that fails
    legality at lookup time (schema drift, hand-edit) is ignored."""
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    params = {"B": 16, "Sp": 16, "A": 128, "C": 128}
    overrides.table().put("bahdanau_attention", params, "float32",
                          {"bblk": 3})  # not a legal tile for B=16
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")  # and it must not warn either
        assert _bblk(16, 16, 128, 128, 4) == 8


def test_env_knob_still_warns_when_illegal(tmp_table, monkeypatch):
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    monkeypatch.setenv("PT_ATTN_BBLK", "6")
    with pytest.warns(UserWarning, match="fails eligibility"):
        assert _bblk(16, 16, 128, 128, 4) == 0


def test_fingerprint_reacts_to_every_source(tmp_table, monkeypatch):
    fp0 = overrides.fingerprint()
    # forced config
    overrides.force("bahdanau_attention", {"bblk": 16})
    fp1 = overrides.fingerprint()
    assert fp1 != fp0
    overrides.force("bahdanau_attention", None)
    assert overrides.fingerprint() == fp0
    # legacy env knob
    monkeypatch.setenv("PT_ATTN_BBLK", "8")
    assert overrides.fingerprint() != fp0
    monkeypatch.delenv("PT_ATTN_BBLK")
    # table content
    overrides.table().put("fused_conv", {"n": 1024, "cin": 128,
                                         "cout": 128}, "bfloat16",
                          {"block_rows": 256})
    assert overrides.fingerprint() != fp0
    # flag
    FLAGS.use_tuned_table = False
    try:
        fp_off = overrides.fingerprint()
    finally:
        FLAGS.use_tuned_table = True
    assert fp_off not in (fp0, overrides.fingerprint())


def test_executor_retraces_on_override_change(tmp_table):
    """The jit-cache-key contract: flipping a kernel knob re-traces
    (one new miss) instead of reusing the stale compiled program."""
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.fc(x, size=4)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"x": np.zeros((2, 4), np.float32)}
    exe.run(feed=feed, fetch_list=[y])
    misses0 = exe.cache_stats["misses"]
    exe.run(feed=feed, fetch_list=[y])
    assert exe.cache_stats["misses"] == misses0  # warm hit
    overrides.force("bahdanau_attention", {"bblk": 4})
    exe.run(feed=feed, fetch_list=[y])
    assert exe.cache_stats["misses"] == misses0 + 1  # knob -> re-trace


# --------------------------------------------------------- harness ------
def test_harness_refuses_to_time_off_tpu():
    assert jax.default_backend() != "tpu"  # the suite's invariant
    with pytest.raises(harness.TuningUnavailable):
        harness.ensure_timeable()
    with pytest.raises(harness.TuningUnavailable):
        harness.tune_case("bahdanau", {"B": 8, "Sp": 16, "A": 128,
                                       "C": 128}, "float32")


def test_harness_loop_mechanics_interpret(tmp_table):
    """The measurement loop itself (candidate sweep, numeric
    cross-check, table write) exercised in interpret mode with the TPU
    requirement waived — production entry points keep require_tpu."""
    t = overrides.table()
    rep = harness.tune_case("bahdanau", {"B": 16, "Sp": 16, "A": 128,
                                         "C": 128}, "float32",
                            table=t, iters=2, warmup=1, require_tpu=False)
    assert {r["config"]["bblk"] for r in rep["rows"]} == {8, 16}
    assert all(r["numerics_ok"] for r in rep["rows"])
    assert rep["best"] in [r["config"] for r in rep["rows"]]
    assert rep["default"] == {"bblk": 8}
    # the winner landed in the table under the runtime's lookup key
    assert t.get("bahdanau_attention",
                 {"B": 16, "Sp": 16, "A": 128, "C": 128},
                 "float32") == rep["best"]


def test_stat_median_of_k():
    from paddle_tpu.profiler import StatSet

    s = StatSet(keep_samples=5)
    for v in (0.5, 0.01, 0.02, 0.03, 100.0):
        s.get("t").add(v)
    assert s.get("t").median == 0.03  # outliers shrugged off
    # default StatSet keeps the zero-overhead aggregate behavior
    s2 = StatSet()
    s2.get("t").add(1.0)
    assert s2.get("t").samples is None
    assert s2.get("t").median == 1.0  # falls back to avg


# -------------------------------------------------- golden numerics ------
@pytest.fixture
def interpret_flag():
    FLAGS.fused_attention_interpret = True
    yield
    FLAGS.fused_attention_interpret = False


def _decoder_inputs(B=16, S=10, T=4, E=128, C=128, A=128, H=128):
    rng = np.random.RandomState(7)
    f32 = jnp.float32
    enc_b = jnp.asarray(rng.randn(B, S, C) * 0.3, f32)
    enc_proj = jnp.asarray(rng.randn(B, S, A) * 0.3, f32)
    lens = rng.randint(S // 2, S + 1, (B,))
    enc_mask = jnp.asarray(np.arange(S)[None, :] < lens[:, None])
    trg_b = jnp.asarray(rng.randn(T, B, E) * 0.3, f32)
    trg_mask = jnp.ones((T, B), f32)
    h0 = jnp.asarray(rng.randn(B, H) * 0.1, f32)
    wa_dec = jnp.asarray(rng.randn(H, A) / np.sqrt(H), f32)
    v_att = jnp.asarray(rng.randn(A) / np.sqrt(A), f32)
    wx = jnp.asarray(rng.randn(E + C, 3 * H) / np.sqrt(E + C), f32)
    wh = jnp.asarray(rng.randn(H, 3 * H) / np.sqrt(H), f32)
    bias = jnp.asarray(rng.randn(3 * H) * 0.05, f32)
    return (enc_b, enc_proj, enc_mask, trg_b, trg_mask, h0, wa_dec,
            v_att, wx, wh, bias)


def test_forced_tuned_config_bit_identical(interpret_flag, tmp_table):
    """Golden numerics: a tuned tile (bblk=16) partitions the batch
    differently but must reproduce the analytic default (bblk=8)
    BIT-FOR-BIT for the forward and every per-row gradient — per-row
    math is tile-invariant. The one principled exception is d(v): its
    reduction crosses batch tiles, so the tile size changes the f32
    summation ORDER (2 partial sums at bblk=8 vs 1 at bblk=16) — that
    gradient is pinned to f32-rounding tightness instead. This is the
    guarantee that lets a tuned table ship without a numerics
    qualification run."""
    from paddle_tpu.ops.bahdanau_kernels import (_bblk,
                                                 fused_attention_decoder)

    args = _decoder_inputs()

    def loss(enc_proj, v_att):
        a = list(args)
        a[1], a[7] = enc_proj, v_att
        return jnp.sum(fused_attention_decoder(*a) ** 2)

    grad_fn = jax.grad(loss, argnums=(0, 1))

    assert _bblk(16, 16, 128, 128, 4) == 8  # analytic default engaged
    h_default = np.asarray(fused_attention_decoder(*args))
    g_default = [np.asarray(g) for g in grad_fn(args[1], args[7])]

    with overrides.forcing("bahdanau_attention", {"bblk": 16}):
        assert _bblk(16, 16, 128, 128, 4) == 16  # tuned tile engaged
        h_tuned = np.asarray(fused_attention_decoder(*args))
        g_tuned = [np.asarray(g) for g in grad_fn(args[1], args[7])]

    np.testing.assert_array_equal(h_tuned, h_default)
    np.testing.assert_array_equal(g_tuned[0], g_default[0])  # d(enc_proj)
    np.testing.assert_allclose(g_tuned[1], g_default[1],     # d(v)
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------------- io/serving ------
def _save_tiny_model(tmp_path):
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.fc(x, size=2, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    model_dir = str(tmp_path / "model")
    pt.io.save_inference_model(model_dir, ["x"], [y])
    return model_dir


def test_meta_json_records_tuning_provenance(tmp_path, tmp_table):
    model_dir = _save_tiny_model(tmp_path)
    with open(os.path.join(model_dir, "meta.json")) as f:
        meta = json.load(f)
    assert meta["tuning"]["device_kind"] == tcache.device_kind()
    assert meta["tuning"]["table_fingerprint"] == \
        overrides.table().fingerprint()


def test_serving_warmup_warns_on_stale_table(tmp_path, tmp_table):
    from paddle_tpu.serving import ServingEngine

    model_dir = _save_tiny_model(tmp_path)
    engine = ServingEngine(model_dir)
    # provenance matches (same process, same table): no warning
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        assert engine.check_tuned_table()
    # the serving host's table changes (retune without re-export):
    overrides.table().put("fused_conv", {"n": 512, "cin": 128,
                                         "cout": 128}, "bfloat16",
                          {"block_rows": 128})
    with pytest.warns(UserWarning, match="stale"):
        assert not engine.check_tuned_table()
    # pre-tuner artifact (no provenance recorded): silently fine
    engine.tuning_meta = None
    with _w.catch_warnings():
        _w.simplefilter("error")
        assert engine.check_tuned_table()


# ------------------------------------------------------ model sweep ------
def test_cases_from_program_finds_flash_sites():
    q = pt.layers.data("q", shape=[1024, 256])
    k = pt.layers.data("k", shape=[1024, 256])
    v = pt.layers.data("v", shape=[1024, 256])
    pt.layers.multi_head_attention(q, k, v, num_heads=2, causal=False)
    sites = space.cases_from_program()
    flash = [s for s in sites if s["family"] == "flash_attention"]
    assert flash and flash[0]["params"] == {"Tq": 1024, "Tk": 1024}
