"""Autotuner subsystem tests (paddle_tpu/tune/).

The contracts under test, in dependency order:
- space: every candidate a generator emits passes the SHARED legality
  predicate, and the runtime accepts exactly that config (the property
  that makes "tuner can never emit an illegal tile" true);
- cache: JSON table round-trips, atomic-ish save, corrupt-file
  recovery, schema-version gating, fingerprint stability;
- overrides: precedence (forced > env > table > analytic), the legacy
  PT_ATTN_BBLK env knob routed through the registry, fingerprint
  reactivity (the Executor jit-cache-key contract);
- harness: the CPU determinism guard (refuses to time off-TPU), and
  the measurement loop mechanics in interpret mode;
- golden numerics: a forced tuned config reproduces the analytic
  default path bit-for-bit (tile size partitions the batch; per-row
  math must be identical);
- io/serving: tuning provenance travels in meta.json and warmup warns
  on a stale table.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu.flags import FLAGS
from paddle_tpu.tune import cache as tcache
from paddle_tpu.tune import harness, overrides, space


@pytest.fixture
def tmp_table(tmp_path):
    path = str(tmp_path / "tuned.json")
    overrides.set_table_path(path)
    yield path
    overrides.reset()


# ----------------------------------------------------------- space ------
BAHDANAU_GRID = [
    # (B, S, A, C, dtype)
    (8, 10, 128, 128, "float32"),
    (16, 60, 512, 512, "bfloat16"),
    (256, 60, 512, 512, "bfloat16"),
    (4, 7, 128, 256, "float32"),
    (2, 100, 128, 128, "bfloat16"),
    (24, 33, 256, 128, "float32"),
]


@pytest.mark.parametrize("B,S,A,C,dtype", BAHDANAU_GRID)
def test_bahdanau_candidates_all_legal(B, S, A, C, dtype):
    """Property: every emitted candidate passes the shared legality
    predicate AND is accepted verbatim by the runtime's _bblk when
    forced — no candidate can compile-fail on Mosaic tile rules."""
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    Sp = space.pad_s(S)
    item = 2 if dtype == "bfloat16" else 4
    params = {"B": B, "Sp": Sp, "A": A, "C": C, "dtype": dtype}
    cands = space.bahdanau_candidates(params)
    assert cands, f"no candidates at {params}"
    for cfg in cands:
        b = cfg["bblk"]
        assert space.bahdanau_blk_legal(b, B, Sp, A, C, item), cfg
        # Mosaic divisibility rules restated independently:
        assert B % b == 0
        assert b % 8 == 0 or b == B
        with overrides.forcing("bahdanau_attention", cfg):
            assert _bblk(B, Sp, A, C, item) == b
    # the analytic default is itself in the candidate set
    default = space.bahdanau_default(params)
    assert default in cands


def test_flash_and_conv_candidates_all_legal():
    for Tq, Tk in [(1024, 1024), (2048, 512), (4096, 4096), (1280, 1280)]:
        cands = space.flash_candidates({"Tq": Tq, "Tk": Tk})
        assert cands
        for cfg in cands:
            assert space.flash_block_legal(cfg["block_q"], cfg["block_k"],
                                           Tq, Tk), (cfg, Tq, Tk)
        assert space.flash_default({"Tq": Tq, "Tk": Tk}) in cands
    for n, cin, cout in [(2048, 128, 512), (1024, 256, 256),
                         (8 * 3 * 7, 128, 128)]:
        params = {"n": n, "cin": cin, "cout": cout, "dtype": "bfloat16"}
        cands = space.conv_candidates(params)
        assert cands
        for cfg in cands:
            assert space.conv_rows_legal(cfg["block_rows"], n, cin, cout, 2)
        assert space.conv_default(params) in cands


def test_rnn_space_matches_runtime_default():
    """The fused_lstm/fused_gru default mirrors lstm_supported /
    gru_supported exactly (same measured windows + hard gates)."""
    from paddle_tpu.ops.pallas_kernels import gru_supported, lstm_supported

    prev = FLAGS.fused_rnn_interpret
    FLAGS.fused_rnn_interpret = True  # neutralize the backend gate
    try:
        for B, H in [(128, 512), (128, 384), (128, 256), (64, 1280),
                     (8, 128), (12, 128)]:
            p = {"B": B, "H": H, "dtype": "bfloat16"}
            assert space._rnn_default("lstm")(p)["fused"] == lstm_supported(
                B, H, "sigmoid", "tanh", "tanh", None, itemsize=2)
            assert space._rnn_default("gru")(p)["fused"] == gru_supported(
                B, H, "sigmoid", "tanh", itemsize=2)
    finally:
        FLAGS.fused_rnn_interpret = prev


# ----------------------------------------------------------- cache ------
def test_cache_round_trip(tmp_path):
    path = str(tmp_path / "t.json")
    t = tcache.TunedTable(path, autoload=False)
    params = {"B": 16, "Sp": 16, "A": 128, "C": 128}
    t.put("bahdanau_attention", params, "float32", {"bblk": 16},
          device="cpu", meta={"median_s": 1e-3})
    fp = t.fingerprint()
    t.save()
    t2 = tcache.TunedTable(path)
    assert t2.get("bahdanau_attention", params, "float32",
                  device="cpu") == {"bblk": 16}
    assert t2.fingerprint() == fp
    # dtype and device are key dimensions: both must miss
    assert t2.get("bahdanau_attention", params, "bfloat16",
                  device="cpu") is None
    assert t2.get("bahdanau_attention", params, "float32",
                  device="tpu-v5-lite") is None
    # a 'dtype' key inside params must not change the signature
    # (space.normalize carries it; runtime lookups don't)
    assert t2.get("bahdanau_attention", dict(params, dtype="float32"),
                  "float32", device="cpu") == {"bblk": 16}


def test_cache_corrupt_file_recovery(tmp_path):
    path = str(tmp_path / "t.json")
    with open(path, "w") as f:
        f.write('{"version": 1, "entries": {truncated')
    with pytest.warns(UserWarning, match="corrupt"):
        t = tcache.TunedTable(path)
    assert len(t) == 0
    assert os.path.exists(path + ".corrupt")
    assert not os.path.exists(path)
    # the quarantined table must not break a subsequent save/load cycle
    t.put("k", {"a": 1}, "float32", {"x": 1}, device="cpu")
    t.save()
    assert tcache.TunedTable(path).get("k", {"a": 1}, "float32",
                                       device="cpu") == {"x": 1}


def test_cache_version_mismatch_ignored(tmp_path):
    path = str(tmp_path / "t.json")
    with open(path, "w") as f:
        json.dump({"version": 999, "entries": {
            "k|a=1|float32|cpu": {"config": {"x": 1}, "meta": {}}}}, f)
    with pytest.warns(UserWarning, match="schema version"):
        t = tcache.TunedTable(path)
    assert len(t) == 0  # analytic defaults apply


def test_cache_missing_file_is_empty(tmp_path):
    t = tcache.TunedTable(str(tmp_path / "absent.json"))
    assert len(t) == 0
    assert t.get("k", {"a": 1}, "float32") is None


# ------------------------------------------------------- overrides ------
def test_override_precedence(tmp_table, monkeypatch):
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    params = {"B": 16, "Sp": 16, "A": 128, "C": 128}
    # table layer
    t = overrides.table()
    t.put("bahdanau_attention", params, "float32", {"bblk": 16})
    assert _bblk(16, 16, 128, 128, 4) == 16
    # env layer beats table (legacy PT_ATTN_BBLK still honored)
    monkeypatch.setenv("PT_ATTN_BBLK", "8")
    assert _bblk(16, 16, 128, 128, 4) == 8
    # programmatic force beats env
    with overrides.forcing("bahdanau_attention", {"bblk": 16}):
        assert _bblk(16, 16, 128, 128, 4) == 16
    # flag kill-switch drops the table layer
    monkeypatch.delenv("PT_ATTN_BBLK")
    FLAGS.use_tuned_table = False
    try:
        assert _bblk(16, 16, 128, 128, 4) == 8  # analytic default
    finally:
        FLAGS.use_tuned_table = True
    assert _bblk(16, 16, 128, 128, 4) == 16


def test_flash_and_conv_consult_overrides(tmp_table):
    """flash_ops._v5e_block_sizes and fused_conv_ops._block_rows
    consult the registry before their analytic defaults."""
    import jax.numpy as jnp2

    from paddle_tpu.ops.flash_ops import _v5e_block_sizes
    from paddle_tpu.ops.fused_conv_ops import _block_rows

    # analytic defaults first
    bs = _v5e_block_sizes(1024, 1024, jnp2.bfloat16)
    assert (bs.block_q, bs.block_k) == (512, 512)
    assert _block_rows(2048, 128, 512, 2) == 1024
    # tuned table entries take over
    t = overrides.table()
    t.put("flash_attention", {"Tq": 1024, "Tk": 1024}, "bfloat16",
          {"block_q": 256, "block_k": 128})
    t.put("fused_conv", {"n": 2048, "cin": 128, "cout": 512}, "bfloat16",
          {"block_rows": 256})
    bs = _v5e_block_sizes(1024, 1024, jnp2.bfloat16)
    assert (bs.block_q, bs.block_k) == (256, 128)
    assert _block_rows(2048, 128, 512, 2) == 256
    # a stale flash entry (doesn't divide T) is ignored, not fatal
    t.put("flash_attention", {"Tq": 512, "Tk": 512}, "bfloat16",
          {"block_q": 768, "block_k": 768})
    bs = _v5e_block_sizes(512, 512, jnp2.bfloat16)
    assert (bs.block_q, bs.block_k) == (512, 512)
    # forced illegal conv block warns and disables the fused path
    with overrides.forcing("fused_conv", {"block_rows": 12}):
        with pytest.warns(UserWarning, match="fails eligibility"):
            assert _block_rows(2048, 128, 512, 2) == 0


def test_rnn_dispatch_consults_overrides(tmp_table):
    """The tuner's {"fused": bool} verdict overrides the measured
    H-window (but can never force an ineligible shape fused)."""
    from paddle_tpu.ops.pallas_kernels import gru_supported

    prev = FLAGS.fused_rnn_interpret
    FLAGS.fused_rnn_interpret = True
    try:
        # H=384 sits outside the GRU measured window -> scan by default
        assert not gru_supported(128, 384, "sigmoid", "tanh", itemsize=2)
        overrides.table().put("fused_gru", {"B": 128, "H": 384},
                              "bfloat16", {"fused": True})
        assert gru_supported(128, 384, "sigmoid", "tanh", itemsize=2)
        # hard illegality (B % 8) wins over any table verdict
        overrides.table().put("fused_gru", {"B": 12, "H": 384},
                              "bfloat16", {"fused": True})
        assert not gru_supported(12, 384, "sigmoid", "tanh", itemsize=2)
    finally:
        FLAGS.fused_rnn_interpret = prev


def test_forced_illegal_warns_and_disables(tmp_table):
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    with overrides.forcing("bahdanau_attention", {"bblk": 3}):
        with pytest.warns(UserWarning, match="fails eligibility"):
            assert _bblk(16, 16, 128, 128, 4) == 0


def test_stale_table_entry_falls_back_to_analytic(tmp_table):
    """A shipped table must never break a model: an entry that fails
    legality at lookup time (schema drift, hand-edit) is ignored."""
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    params = {"B": 16, "Sp": 16, "A": 128, "C": 128}
    overrides.table().put("bahdanau_attention", params, "float32",
                          {"bblk": 3})  # not a legal tile for B=16
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")  # and it must not warn either
        assert _bblk(16, 16, 128, 128, 4) == 8


def test_env_knob_still_warns_when_illegal(tmp_table, monkeypatch):
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    monkeypatch.setenv("PT_ATTN_BBLK", "6")
    with pytest.warns(UserWarning, match="fails eligibility"):
        assert _bblk(16, 16, 128, 128, 4) == 0


def test_fingerprint_reacts_to_every_source(tmp_table, monkeypatch):
    fp0 = overrides.fingerprint()
    # forced config
    overrides.force("bahdanau_attention", {"bblk": 16})
    fp1 = overrides.fingerprint()
    assert fp1 != fp0
    overrides.force("bahdanau_attention", None)
    assert overrides.fingerprint() == fp0
    # legacy env knob
    monkeypatch.setenv("PT_ATTN_BBLK", "8")
    assert overrides.fingerprint() != fp0
    monkeypatch.delenv("PT_ATTN_BBLK")
    # table content
    overrides.table().put("fused_conv", {"n": 1024, "cin": 128,
                                         "cout": 128}, "bfloat16",
                          {"block_rows": 256})
    assert overrides.fingerprint() != fp0
    # flag
    FLAGS.use_tuned_table = False
    try:
        fp_off = overrides.fingerprint()
    finally:
        FLAGS.use_tuned_table = True
    assert fp_off not in (fp0, overrides.fingerprint())


def test_executor_retraces_on_override_change(tmp_table):
    """The jit-cache-key contract: flipping a kernel knob re-traces
    (one new miss) instead of reusing the stale compiled program."""
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.fc(x, size=4)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = {"x": np.zeros((2, 4), np.float32)}
    exe.run(feed=feed, fetch_list=[y])
    misses0 = exe.cache_stats["misses"]
    exe.run(feed=feed, fetch_list=[y])
    assert exe.cache_stats["misses"] == misses0  # warm hit
    overrides.force("bahdanau_attention", {"bblk": 4})
    exe.run(feed=feed, fetch_list=[y])
    assert exe.cache_stats["misses"] == misses0 + 1  # knob -> re-trace


# --------------------------------------------------------- harness ------
def test_harness_refuses_to_time_off_tpu():
    assert jax.default_backend() != "tpu"  # the suite's invariant
    with pytest.raises(harness.TuningUnavailable):
        harness.ensure_timeable()
    with pytest.raises(harness.TuningUnavailable):
        harness.tune_case("bahdanau", {"B": 8, "Sp": 16, "A": 128,
                                       "C": 128}, "float32")


def test_harness_loop_mechanics_interpret(tmp_table):
    """The measurement loop itself (candidate sweep, numeric
    cross-check, table write) exercised in interpret mode with the TPU
    requirement waived — production entry points keep require_tpu."""
    t = overrides.table()
    rep = harness.tune_case("bahdanau", {"B": 16, "Sp": 16, "A": 128,
                                         "C": 128}, "float32",
                            table=t, iters=2, warmup=1, require_tpu=False)
    assert {r["config"]["bblk"] for r in rep["rows"]} == {8, 16}
    assert all(r["numerics_ok"] for r in rep["rows"])
    assert rep["best"] in [r["config"] for r in rep["rows"]]
    assert rep["default"] == {"bblk": 8}
    # the winner landed in the table under the runtime's lookup key
    assert t.get("bahdanau_attention",
                 {"B": 16, "Sp": 16, "A": 128, "C": 128},
                 "float32") == rep["best"]


def test_stat_median_of_k():
    from paddle_tpu.profiler import StatSet

    s = StatSet(keep_samples=5)
    for v in (0.5, 0.01, 0.02, 0.03, 100.0):
        s.get("t").add(v)
    assert s.get("t").median == 0.03  # outliers shrugged off
    # default StatSet keeps the zero-overhead aggregate behavior
    s2 = StatSet()
    s2.get("t").add(1.0)
    assert s2.get("t").samples is None
    assert s2.get("t").median == 1.0  # falls back to avg


# -------------------------------------------------- golden numerics ------
@pytest.fixture
def interpret_flag():
    FLAGS.fused_attention_interpret = True
    yield
    FLAGS.fused_attention_interpret = False


def _decoder_inputs(B=16, S=10, T=4, E=128, C=128, A=128, H=128):
    rng = np.random.RandomState(7)
    f32 = jnp.float32
    enc_b = jnp.asarray(rng.randn(B, S, C) * 0.3, f32)
    enc_proj = jnp.asarray(rng.randn(B, S, A) * 0.3, f32)
    lens = rng.randint(S // 2, S + 1, (B,))
    enc_mask = jnp.asarray(np.arange(S)[None, :] < lens[:, None])
    trg_b = jnp.asarray(rng.randn(T, B, E) * 0.3, f32)
    trg_mask = jnp.ones((T, B), f32)
    h0 = jnp.asarray(rng.randn(B, H) * 0.1, f32)
    wa_dec = jnp.asarray(rng.randn(H, A) / np.sqrt(H), f32)
    v_att = jnp.asarray(rng.randn(A) / np.sqrt(A), f32)
    wx = jnp.asarray(rng.randn(E + C, 3 * H) / np.sqrt(E + C), f32)
    wh = jnp.asarray(rng.randn(H, 3 * H) / np.sqrt(H), f32)
    bias = jnp.asarray(rng.randn(3 * H) * 0.05, f32)
    return (enc_b, enc_proj, enc_mask, trg_b, trg_mask, h0, wa_dec,
            v_att, wx, wh, bias)


def test_forced_tuned_config_bit_identical(interpret_flag, tmp_table):
    """Golden numerics: a tuned tile (bblk=16) partitions the batch
    differently but must reproduce the analytic default (bblk=8)
    BIT-FOR-BIT for the forward and every per-row gradient — per-row
    math is tile-invariant. The one principled exception is d(v): its
    reduction crosses batch tiles, so the tile size changes the f32
    summation ORDER (2 partial sums at bblk=8 vs 1 at bblk=16) — that
    gradient is pinned to f32-rounding tightness instead. This is the
    guarantee that lets a tuned table ship without a numerics
    qualification run."""
    from paddle_tpu.ops.bahdanau_kernels import (_bblk,
                                                 fused_attention_decoder)

    args = _decoder_inputs()

    def loss(enc_proj, v_att):
        a = list(args)
        a[1], a[7] = enc_proj, v_att
        return jnp.sum(fused_attention_decoder(*a) ** 2)

    grad_fn = jax.grad(loss, argnums=(0, 1))

    assert _bblk(16, 16, 128, 128, 4) == 8  # analytic default engaged
    h_default = np.asarray(fused_attention_decoder(*args))
    g_default = [np.asarray(g) for g in grad_fn(args[1], args[7])]

    with overrides.forcing("bahdanau_attention", {"bblk": 16}):
        assert _bblk(16, 16, 128, 128, 4) == 16  # tuned tile engaged
        h_tuned = np.asarray(fused_attention_decoder(*args))
        g_tuned = [np.asarray(g) for g in grad_fn(args[1], args[7])]

    np.testing.assert_array_equal(h_tuned, h_default)
    np.testing.assert_array_equal(g_tuned[0], g_default[0])  # d(enc_proj)
    np.testing.assert_allclose(g_tuned[1], g_default[1],     # d(v)
                               rtol=1e-5, atol=1e-6)


# ------------------------------------------------------- io/serving ------
def _save_tiny_model(tmp_path):
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.fc(x, size=2, act="softmax")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    model_dir = str(tmp_path / "model")
    pt.io.save_inference_model(model_dir, ["x"], [y])
    return model_dir


def test_meta_json_records_tuning_provenance(tmp_path, tmp_table):
    model_dir = _save_tiny_model(tmp_path)
    with open(os.path.join(model_dir, "meta.json")) as f:
        meta = json.load(f)
    assert meta["tuning"]["device_kind"] == tcache.device_kind()
    assert meta["tuning"]["table_fingerprint"] == \
        overrides.table().fingerprint()


def test_serving_warmup_warns_on_stale_table(tmp_path, tmp_table):
    from paddle_tpu.serving import ServingEngine

    model_dir = _save_tiny_model(tmp_path)
    engine = ServingEngine(model_dir)
    # provenance matches (same process, same table): no warning
    import warnings as _w

    with _w.catch_warnings():
        _w.simplefilter("error")
        assert engine.check_tuned_table()
    # the serving host's table changes (retune without re-export):
    overrides.table().put("fused_conv", {"n": 512, "cin": 128,
                                         "cout": 128}, "bfloat16",
                          {"block_rows": 128})
    with pytest.warns(UserWarning, match="stale"):
        assert not engine.check_tuned_table()
    # pre-tuner artifact (no provenance recorded): silently fine
    engine.tuning_meta = None
    with _w.catch_warnings():
        _w.simplefilter("error")
        assert engine.check_tuned_table()


# ------------------------------------------------------ model sweep ------
def test_cases_from_program_finds_flash_sites():
    q = pt.layers.data("q", shape=[1024, 256])
    k = pt.layers.data("k", shape=[1024, 256])
    v = pt.layers.data("v", shape=[1024, 256])
    pt.layers.multi_head_attention(q, k, v, num_heads=2, causal=False)
    sites = space.cases_from_program()
    flash = [s for s in sites if s["family"] == "flash_attention"]
    assert flash and flash[0]["params"] == {"Tq": 1024, "Tk": 1024}


def _build_decoder_program(B=16, C=32, A=24, S=8):
    enc = pt.layers.data("enc", shape=[B, S, C], append_batch_size=False,
                         lod_level=1)
    trg = pt.layers.data("trg", shape=[B, 6], append_batch_size=False,
                         lod_level=1)
    boot = pt.layers.data("boot", shape=[B, A], append_batch_size=False)
    pt.layers.attention_gru_decoder(enc, trg, boot, size=A,
                                    src_max_len=S, trg_max_len=S)


def test_cases_from_program_mesh_local_batch():
    """ISSUE-10 tentpole (d): under a dp mesh the fused kernels
    dispatch at the PER-SHARD batch (mesh_dispatch.local_batch), so the
    sweep must key tuning cases on B/dp — and skip sites dp does not
    divide (the runtime scans there; a global-batch entry would tune a
    shape that never dispatches)."""
    _build_decoder_program(B=16)
    bah = [s for s in space.cases_from_program()
           if s["family"] == "bahdanau_attention"]
    assert bah and bah[0]["params"]["B"] == 16
    bah4 = [s for s in space.cases_from_program(dp=4)
            if s["family"] == "bahdanau_attention"]
    assert bah4 and bah4[0]["params"]["B"] == 4
    # everything but the batch is shard-invariant
    assert {k: v for k, v in bah4[0]["params"].items() if k != "B"} == \
        {k: v for k, v in bah[0]["params"].items() if k != "B"}
    # non-divisible dp: the site is skipped, not mis-keyed
    assert not [s for s in space.cases_from_program(dp=3)
                if s["family"] == "bahdanau_attention"]
    # flash keys on sequence lengths only — dp leaves it untouched
    pt.reset()
    q = pt.layers.data("q", shape=[1024, 256])
    pt.layers.multi_head_attention(q, num_heads=2, causal=False)
    f1 = [s for s in space.cases_from_program()
          if s["family"] == "flash_attention"]
    f4 = [s for s in space.cases_from_program(dp=4)
          if s["family"] == "flash_attention"]
    assert f1 and [s["params"] for s in f1] == [s["params"] for s in f4]


# ===================================================== Autotuner v2 ======
# -------------------------------------------------- shape interpolation --
def _put_cpu(t, fam, params, dtype, cfg, **meta_kw):
    t.put(fam, params, dtype, cfg, **meta_kw)


def test_consult_order_forced_env_exact_interpolated_analytic(
        tmp_table, monkeypatch):
    """THE v2 precedence chain, one layer peeled off at a time."""
    params = {"B": 16, "Sp": 16, "A": 128, "C": 128}
    near = {"B": 32, "Sp": 16, "A": 128, "C": 128}
    t = overrides.table()
    t.put("bahdanau_attention", near, "float32", {"bblk": 8})
    t.put("bahdanau_attention", params, "float32", {"bblk": 16})
    monkeypatch.setenv("PT_ATTN_BBLK", "4")
    with overrides.forcing("bahdanau_attention", {"bblk": 2}):
        ov = overrides.lookup("bahdanau_attention", params, "float32")
        assert (ov.config, ov.source) == ({"bblk": 2}, "forced")
    ov = overrides.lookup("bahdanau_attention", params, "float32")
    assert (ov.config, ov.source) == ({"bblk": 4}, "env")
    monkeypatch.delenv("PT_ATTN_BBLK")
    ov = overrides.lookup("bahdanau_attention", params, "float32")
    assert (ov.config, ov.source) == ({"bblk": 16}, "table")
    # drop the exact entry -> nearest neighbor (B=32, one octave away)
    t.entries.pop(tcache.entry_key(
        "bahdanau_attention", tcache.make_sig(params), "float32",
        tcache.device_kind()))
    t._lru.clear()
    t._fp = None
    ov = overrides.lookup("bahdanau_attention", params, "float32")
    assert (ov.config, ov.source) == ({"bblk": 8}, "interpolated")
    assert ov.origin == tcache.make_sig(near)
    # interpolation off -> analytic (None)
    FLAGS.tune_interpolate = False
    try:
        assert overrides.lookup("bahdanau_attention", params,
                                "float32") is None
    finally:
        FLAGS.tune_interpolate = True
    # empty pool -> analytic
    t.entries.clear()
    t._lru.clear()
    t._fp = None
    assert overrides.lookup("bahdanau_attention", params, "float32") is None


INTERP_TARGETS = [
    # neighbors whose configs are NOT legal at the target must be
    # rejected by the re-check, never returned
    ({"B": 16, "Sp": 16, "A": 128, "C": 128}, "float32"),
    ({"B": 24, "Sp": 32, "A": 128, "C": 128}, "float32"),
    ({"B": 8, "Sp": 16, "A": 128, "C": 128}, "bfloat16"),
    ({"B": 48, "Sp": 48, "A": 256, "C": 128}, "bfloat16"),
    ({"B": 128, "Sp": 64, "A": 512, "C": 512}, "bfloat16"),
]


def test_interpolated_config_always_legal_property(tmp_table):
    """Property (ISSUE-10 acceptance): whatever is in the neighbor
    pool, an interpolated consult either returns a config that passes
    space.config_legal for the TARGET shape, or returns nothing. The
    pool deliberately mixes legal tiles, tiles only legal at their own
    shape (bblk=32/64), and garbage."""
    t = overrides.table()
    pool = [
        ({"B": 32, "Sp": 16, "A": 128, "C": 128}, "float32", {"bblk": 32}),
        ({"B": 64, "Sp": 16, "A": 128, "C": 128}, "float32", {"bblk": 64}),
        ({"B": 32, "Sp": 32, "A": 128, "C": 128}, "float32", {"bblk": 8}),
        ({"B": 16, "Sp": 32, "A": 128, "C": 128}, "bfloat16", {"bblk": 8}),
        ({"B": 64, "Sp": 64, "A": 256, "C": 128}, "bfloat16", {"bblk": 8}),
        ({"B": 96, "Sp": 64, "A": 512, "C": 512}, "bfloat16", {"bblk": 8}),
        ({"B": 32, "Sp": 16, "A": 128, "C": 128}, "float32",
         {"bogus": "x"}),
    ]
    for p, dt, cfg in pool:
        t.put("bahdanau_attention", p, dt, cfg)
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    for params, dtype in INTERP_TARGETS:
        ov = overrides.lookup("bahdanau_attention", params, dtype)
        if ov is not None and ov.source == "interpolated":
            assert space.config_legal("bahdanau_attention", params,
                                      dtype, ov.config), (params, ov)
        # and the runtime consult can never produce an illegal tile:
        item = 2 if dtype == "bfloat16" else 4
        b = _bblk(params["B"], params["Sp"], params["A"], params["C"],
                  item)
        if b:
            assert space.bahdanau_blk_legal(
                b, params["B"], params["Sp"], params["A"], params["C"],
                item)


def test_interpolation_rejects_illegal_neighbor_falls_to_analytic(
        tmp_table):
    """The NEAREST neighbor's config is illegal at the target (bblk=32
    does not divide B=24): the re-check must skip it and take the next
    legal neighbor; with no other neighbor, analytic (None)."""
    t = overrides.table()
    target = {"B": 24, "Sp": 16, "A": 128, "C": 128}
    t.put("bahdanau_attention", {"B": 32, "Sp": 16, "A": 128, "C": 128},
          "float32", {"bblk": 32})  # nearest, illegal at B=24
    assert overrides.lookup("bahdanau_attention", target,
                            "float32") is None
    t.put("bahdanau_attention", {"B": 48, "Sp": 16, "A": 128, "C": 128},
          "float32", {"bblk": 8})  # farther, legal at B=24
    overrides.reload_table()  # drop the memoized miss
    t = overrides.table()
    t.put("bahdanau_attention", {"B": 32, "Sp": 16, "A": 128, "C": 128},
          "float32", {"bblk": 32})
    t.put("bahdanau_attention", {"B": 48, "Sp": 16, "A": 128, "C": 128},
          "float32", {"bblk": 8})
    ov = overrides.lookup("bahdanau_attention", target, "float32")
    assert ov is not None and ov.source == "interpolated"
    assert ov.config == {"bblk": 8}


def test_interpolation_respects_distance_cap(tmp_table):
    """A donor beyond INTERP_MAX_DIST (B=128 vs B=8 is ~2.8 octaves =
    ln(16) > 1.5) must not transfer — far shapes have different tile
    economics and the analytic default is the better guess."""
    t = overrides.table()
    t.put("bahdanau_attention", {"B": 128, "Sp": 16, "A": 128, "C": 128},
          "float32", {"bblk": 8})
    assert overrides.lookup(
        "bahdanau_attention", {"B": 8, "Sp": 16, "A": 128, "C": 128},
        "float32") is None


def test_runtime_consult_uses_interpolated_tile(tmp_table):
    """End to end through the kernel's own consult point: _bblk at an
    untuned shape picks up the neighbor's tile when legal (and the
    golden-numerics test already proves any legal tile is
    bit-identical)."""
    from paddle_tpu.ops.bahdanau_kernels import _bblk

    t = overrides.table()
    t.put("bahdanau_attention", {"B": 32, "Sp": 16, "A": 128, "C": 128},
          "float32", {"bblk": 16})
    # B=16: tile 16 is legal (spans nothing illegal) -> interpolated win
    assert _bblk(16, 16, 128, 128, 4) == 16
    st = overrides.consult_stats()
    assert st["interpolated"] >= 1


# ------------------------------------------------- fleet database --------
def test_merge_precedence_measured_beats_interpolated_then_newer():
    measured_old = {"config": {"bblk": 8},
                    "meta": {"provenance": "measured", "updated_at": 100}}
    measured_new = {"config": {"bblk": 16},
                    "meta": {"provenance": "measured", "updated_at": 200}}
    interp_newer = {"config": {"bblk": 4},
                    "meta": {"provenance": "interpolated",
                             "updated_at": 999}}
    legacy = {"config": {"bblk": 2}, "meta": {}}
    # measured beats interpolated regardless of age
    assert tcache.merge_entry(measured_old, interp_newer) is measured_old
    assert tcache.merge_entry(interp_newer, measured_old) is measured_old
    # same provenance: newest wins; ties keep the incumbent
    assert tcache.merge_entry(measured_old, measured_new) is measured_new
    assert tcache.merge_entry(measured_new, measured_old) is measured_new
    assert tcache.merge_entry(measured_old, measured_old) is measured_old
    # anything beats a legacy no-provenance entry
    assert tcache.merge_entry(legacy, interp_newer) is interp_newer
    assert tcache.merge_entry(interp_newer, legacy) is interp_newer
    # absent incumbent: theirs
    assert tcache.merge_entry(None, legacy) is legacy


def test_table_merge_from_stats(tmp_path):
    a = tcache.TunedTable(str(tmp_path / "a.json"), autoload=False)
    b = tcache.TunedTable(str(tmp_path / "b.json"), autoload=False)
    p1, p2, p3 = ({"B": 8, "H": 128}, {"B": 16, "H": 128},
                  {"B": 32, "H": 128})
    a.put("fused_gru", p1, "bfloat16", {"fused": True},
          device="d", meta={"provenance": "measured", "updated_at": 10})
    a.put("fused_gru", p2, "bfloat16", {"fused": True},
          device="d", meta={"provenance": "interpolated",
                            "updated_at": 10})
    b.put("fused_gru", p1, "bfloat16", {"fused": False},
          device="d", meta={"provenance": "interpolated",
                            "updated_at": 99})   # loses: interp vs measured
    b.put("fused_gru", p2, "bfloat16", {"fused": False},
          device="d", meta={"provenance": "measured", "updated_at": 5})
    b.put("fused_gru", p3, "bfloat16", {"fused": True},
          device="d", meta={"provenance": "measured", "updated_at": 5})
    st = a.merge_from(b)
    assert st == {"added": 1, "replaced": 1, "kept": 1}
    assert a.get("fused_gru", p1, "bfloat16", device="d") == {"fused": True}
    assert a.get("fused_gru", p2, "bfloat16", device="d") == {
        "fused": False}
    assert a.get("fused_gru", p3, "bfloat16", device="d") == {"fused": True}


def test_export_import_round_trip_bit_identical(tmp_path):
    """export -> import into empty -> export again: BYTE-identical
    files (the fleet exchange contract: moving a table through a
    colleague's machine must not mutate it)."""
    src = tcache.TunedTable(str(tmp_path / "src.json"), autoload=False)
    src.put("bahdanau_attention", {"B": 256, "Sp": 64, "A": 512,
                                   "C": 512},
            "bfloat16", {"bblk": 8}, device="tpu-v5-lite",
            meta={"provenance": "measured", "updated_at": 123,
                  "median_s": 3.2e-4})
    src.put("flash_attention", {"Tq": 2048, "Tk": 2048}, "bfloat16",
            {"block_q": 512, "block_k": 512}, device="tpu-v5-lite",
            meta={"provenance": "measured", "updated_at": 124})
    exp1 = str(tmp_path / "exp1.json")
    src.save(exp1)
    mid = tcache.TunedTable(str(tmp_path / "mid.json"), autoload=False)
    mid.merge_from(tcache.load_strict(exp1))
    exp2 = str(tmp_path / "exp2.json")
    mid.save(exp2)
    with open(exp1, "rb") as f1, open(exp2, "rb") as f2:
        assert f1.read() == f2.read()
    assert mid.fingerprint() == src.fingerprint()


def test_import_schema_version_gated(tmp_path):
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"version": 999, "entries": {}}))
    with pytest.raises(tcache.TableFormatError, match="schema version"):
        tcache.load_strict(str(bad))
    trunc = tmp_path / "trunc.json"
    trunc.write_text('{"version": 1, "entries": {oops')
    with pytest.raises(tcache.TableFormatError, match="not JSON"):
        tcache.load_strict(str(trunc))
    malformed = tmp_path / "mal.json"
    malformed.write_text(json.dumps(
        {"version": 1, "entries": {"k": {"config": 7}}}))
    with pytest.raises(tcache.TableFormatError, match="malformed"):
        tcache.load_strict(str(malformed))


def test_base_table_read_through(tmp_table, tmp_path, monkeypatch):
    """A shipped per-device base table is consulted beneath the local
    table: base-only keys hit (source "table"), a local entry shadows
    the base one, and the base feeds the interpolation pool. The
    overrides fingerprint must react to the base layer (jit-cache-key
    contract)."""
    base_dir = tmp_path / "tables"
    base_dir.mkdir()
    base = tcache.TunedTable(
        str(base_dir / f"{tcache.device_kind()}.json"), autoload=False)
    pA = {"B": 64, "Sp": 16, "A": 128, "C": 128}
    pB = {"B": 32, "Sp": 16, "A": 128, "C": 128}
    base.put("bahdanau_attention", pA, "float32", {"bblk": 64},
             provenance="measured")
    base.put("bahdanau_attention", pB, "float32", {"bblk": 32},
             provenance="measured")
    base.save()
    fp_nobase = overrides.fingerprint()
    monkeypatch.setenv("PT_TUNE_TABLES_DIR", str(base_dir))
    overrides.reload_table()
    assert overrides.fingerprint() != fp_nobase
    # base-only key: read-through hit
    ov = overrides.lookup("bahdanau_attention", pA, "float32")
    assert (ov.config, ov.source) == ({"bblk": 64}, "table")
    # local entry shadows the base layer
    overrides.table().put("bahdanau_attention", pA, "float32",
                          {"bblk": 8})
    ov = overrides.lookup("bahdanau_attention", pA, "float32")
    assert ov.config == {"bblk": 8}
    # base entries seed interpolation for nearby shapes (B=16 target:
    # nearest donor is pB at one octave; its bblk=32 is illegal at
    # B=16 -> next duty falls to the legal local bblk=8 at pA)
    ov = overrides.lookup(
        "bahdanau_attention", {"B": 16, "Sp": 16, "A": 128, "C": 128},
        "float32")
    assert ov is not None and ov.source == "interpolated"
    assert space.config_legal(
        "bahdanau_attention", {"B": 16, "Sp": 16, "A": 128, "C": 128},
        "float32", ov.config)


def test_shipped_v5lite_base_table_is_valid():
    """The table the package actually ships: loads strict (current
    schema), every entry is keyed for tpu-v5-lite with measured
    provenance, and every config passes its OWN shape's legality —
    shipping can never hand any device an illegal tile, and on CPU
    (device_kind 'cpu') it is never even consulted."""
    path = os.path.join(os.path.dirname(space.__file__), "tables",
                        "tpu-v5-lite.json")
    t = tcache.load_strict(path)
    assert len(t) >= 20
    for key, e in t.entries.items():
        kernel, sig, dtype, device = tcache.parse_key(key)
        assert device == "tpu-v5-lite"
        assert e["meta"]["provenance"] == "measured"
        params = tcache.sig_to_params(sig)
        assert space.config_legal(kernel, params, dtype, e["config"]), key
    # and the default CPU base-table resolution ignores it
    assert tcache.base_table_path() is None


# ------------------------------------------------ provenance counters ----
def test_consult_counters_and_metrics_export(tmp_table):
    pt.reset()  # zero the counters
    overrides.set_table_path(tmp_table)
    t = overrides.table()
    params = {"B": 16, "Sp": 16, "A": 128, "C": 128}
    assert overrides.lookup("bahdanau_attention", params,
                            "float32") is None  # analytic
    t.put("bahdanau_attention", params, "float32", {"bblk": 8})
    overrides.lookup("bahdanau_attention", params, "float32")  # table
    t.put("bahdanau_attention", {"B": 32, "Sp": 16, "A": 128, "C": 128},
          "float32", {"bblk": 8})
    overrides.lookup("bahdanau_attention",
                     {"B": 64, "Sp": 16, "A": 128, "C": 128},
                     "float32")  # interpolated (B=32 donor, legal)
    with overrides.forcing("bahdanau_attention", {"bblk": 8}):
        overrides.lookup("bahdanau_attention", params, "float32")
    st = overrides.consult_stats()
    assert st["analytic"] >= 1 and st["table"] >= 1
    assert st["interpolated"] >= 1 and st["forced"] >= 1
    # the unified registry renders every source label, 0s included
    from paddle_tpu.obs import metrics as obs_metrics
    from paddle_tpu.obs import promparse

    text = obs_metrics.registry().render()
    fams = promparse.parse_text(text)
    series = {lb["source"]: v for _, lb, v in
              fams["pt_tune_consults_total"].samples}
    assert set(series) == {"forced", "env", "table", "interpolated",
                           "analytic"}
    assert series["env"] == 0
    assert series["interpolated"] >= 1
    # classify() must NOT move the counters (warmup coverage contract)
    before = overrides.consult_stats()
    overrides.classify("bahdanau_attention", params, "float32")
    assert overrides.consult_stats() == before


def test_engine_decode_tune_cases_mesh_local(tmp_path, tmp_table):
    """ISSUE-10 tentpole (d), serving side: a mesh replica's decode
    tune cases key on the PER-SHARD batch (bucket/dp), and buckets the
    dp axis does not divide are skipped — mirroring what the fused
    kernels actually dispatch inside shard_map."""
    from paddle_tpu.parallel import mesh_from_spec
    from paddle_tpu.serving import BucketPolicy, ServingEngine

    enc = pt.layers.data("enc", shape=[8, 8, 128],
                         append_batch_size=False, lod_level=1)
    trg = pt.layers.data("trg", shape=[8, 6], append_batch_size=False,
                         lod_level=1)
    boot = pt.layers.data("boot", shape=[8, 128],
                          append_batch_size=False)
    dec = pt.layers.attention_gru_decoder(enc, trg, boot, size=128,
                                          src_max_len=8, trg_max_len=8)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    d = str(tmp_path / "dec_model")
    pt.io.save_inference_model(d, ["enc", "trg", "boot"], [dec])

    pol = BucketPolicy(max_batch_size=4, batch_buckets=(2, 4))
    single = ServingEngine(d, policy=pol)
    b_single = sorted(c["params"]["B"] for c in single.decode_tune_cases()
                      if c["family"] == "bahdanau_attention")
    assert b_single == [2, 4]  # the bucket grid itself, K=1
    meshed = ServingEngine(d, policy=pol, mesh=mesh_from_spec("dp2"))
    b_mesh = sorted(c["params"]["B"] for c in meshed.decode_tune_cases()
                    if c["family"] == "bahdanau_attention")
    assert b_mesh == [1, 2]  # per-shard: bucket/dp
    # coverage classification keys on the same per-shard shapes
    # (Sp = pad_s(8) = 16; B=4 is the program's own concrete-batch site
    # 8/dp — also per-shard via cases_from_program(dp=2))
    sigs = {c["sig"] for c in meshed.tune_coverage()
            if c["family"] == "bahdanau_attention"}
    assert sigs == {"A=128,B=1,C=128,Sp=16", "A=128,B=2,C=128,Sp=16",
                    "A=128,B=4,C=128,Sp=16"}


# ------------------------------------------- warmup coverage report ------
def test_serving_warmup_names_untuned_and_interpolated(tmp_path,
                                                       tmp_table):
    """The upgraded stale-table warning: names WHICH kernels/shapes are
    untuned vs interpolated and gives the actionable tune command."""
    from paddle_tpu.serving import ServingEngine

    q = pt.layers.data("q", shape=[1024, 256])
    out = pt.layers.multi_head_attention(q, num_heads=2, causal=False)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    model_dir = str(tmp_path / "model")
    pt.io.save_inference_model(model_dir, ["q"], [out])
    engine = ServingEngine(model_dir)
    # make provenance stale so the warning fires
    overrides.table().put("fused_conv", {"n": 512, "cin": 128,
                                         "cout": 128}, "bfloat16",
                          {"block_rows": 128})
    with pytest.warns(UserWarning) as rec:
        assert not engine.check_tuned_table()
    msg = "\n".join(str(w.message) for w in rec)
    assert "untuned (analytic defaults)" in msg
    assert "flash_attention[Tk=1024,Tq=1024" in msg
    assert "paddle_tpu tune" in msg
    # tune the shape's neighbor -> same site reports interpolated
    overrides.table().put("flash_attention", {"Tq": 2048, "Tk": 2048},
                          "float32", {"block_q": 512, "block_k": 512})
    cov = engine.tune_coverage()
    flash = [c for c in cov if c["family"] == "flash_attention"]
    assert flash and flash[0]["source"] == "interpolated"
    assert flash[0]["origin"] == "Tk=2048,Tq=2048"
    with pytest.warns(UserWarning) as rec:
        engine.check_tuned_table()
    msg = "\n".join(str(w.message) for w in rec)
    assert "interpolated from nearby shapes" in msg
    # exact-tune the shape -> coverage goes clean, warning loses it
    overrides.table().put("flash_attention", {"Tq": 1024, "Tk": 1024},
                          "float32", {"block_q": 512, "block_k": 512})
    cov = engine.tune_coverage()
    flash = [c for c in cov if c["family"] == "flash_attention"]
    assert flash and flash[0]["source"] == "table"
