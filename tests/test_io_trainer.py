"""IO (save/load, inference model, checkpoints), Trainer events, grad-check.

Reference test parity: fluid tests for io.py (save/load persistables,
save_inference_model), v2 trainer event protocol, Trainer.cpp checkgrad
mode, ParamUtil checkpoint cadence/resume.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.data import batch


def _build_regression():
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    return x, y, pred, loss


def _toy_feed(n=16, seed=0):
    rng = np.random.RandomState(seed)
    xs = rng.randn(n, 4).astype(np.float32)
    ys = (xs @ np.array([[1.0], [-2.0], [0.5], [3.0]], np.float32) + 0.7).astype(
        np.float32
    )
    return {"x": xs, "y": ys}


def test_save_load_persistables_roundtrip(tmp_path):
    x, y, pred, loss = _build_regression()
    pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = _toy_feed()
    for _ in range(3):
        exe.run(feed=feed, fetch_list=[loss])

    d = str(tmp_path / "ckpt")
    pt.io.save_persistables(d)
    scope = pt.global_scope()
    saved = {n: np.array(np.asarray(scope.get(n))) for n in scope.keys()
             if not n.startswith("@")}

    # clobber, restore, compare (optimizer moments included)
    for n in saved:
        scope.set(n, np.zeros_like(saved[n]))
    pt.io.load_persistables(d)
    for n, v in saved.items():
        np.testing.assert_array_equal(np.asarray(scope.get(n)), v)

    # training continues bit-identically after restore (optimizer moments
    # must round-trip, not just parameter values)
    prog = pt.default_main_program()
    prog.random_seed = 13  # dropout-free net, but pin the RNG regardless
    (l1,) = exe.run(feed=feed, fetch_list=[loss])
    pt.io.load_persistables(d)
    (l2,) = exe.run(feed=feed, fetch_list=[loss])
    np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))


def test_save_inference_model_prunes_optimizer(tmp_path):
    x, y, pred, loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feed = _toy_feed()
    exe.run(feed=feed, fetch_list=[loss])  # one training step
    test_prog = pt.default_main_program().clone(for_test=True)
    (before,) = exe.run(test_prog, feed=feed, fetch_list=[pred.name])

    d = str(tmp_path / "model")
    pt.io.save_inference_model(d, ["x"], [pred])

    pt.reset()
    prog, feed_names, fetch_names = pt.io.load_inference_model(d)
    assert feed_names == ["x"]
    assert fetch_names == [pred.name]
    # pruned program must not contain label input, autodiff, or sgd ops
    types = [op.type for op in prog.global_block().ops]
    assert "autodiff" not in types and "sgd" not in types
    (after,) = pt.Executor().run(
        prog, feed={"x": feed["x"]}, fetch_list=[fetch_names[0]]
    )
    np.testing.assert_allclose(np.asarray(after), np.asarray(before), rtol=1e-6)


def test_checkpoint_rotation_and_resume(tmp_path):
    d = str(tmp_path / "ck")
    x, y, pred, loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    for i in range(5):
        s = pt.io.save_checkpoint(d, {"pass_id": i, "step": i * 10},
                                  max_num_checkpoints=2)
        assert s == i
    assert pt.io.get_latest_checkpoint_serial(d) == 4
    args = pt.io.load_checkpoint(d)
    assert args["pass_id"] == 4 and args["step"] == 40
    # only 2 kept
    import os
    kept = [n for n in os.listdir(d) if n.startswith("checkpoint_")]
    assert sorted(kept) == ["checkpoint_3", "checkpoint_4"]


def test_trainer_events_convergence_and_test_program(windowed):
    x, y, pred, loss = _build_regression()
    acc_like = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)

    feed = _toy_feed(32)

    def reader():
        for i in range(8):
            yield {"x": feed["x"][i * 4:(i + 1) * 4],
                   "y": feed["y"][i * 4:(i + 1) * 4]}

    events = []
    trainer = pt.Trainer(loss)
    metrics = trainer.train(
        reader,
        num_passes=20,
        event_handler=lambda e: events.append(type(e).__name__),
        test_reader=reader,
    )
    assert metrics["cost"] < 0.5, metrics
    assert metrics["test_cost"] < 0.5, metrics
    assert events[0] == "BeginPass" and "EndIteration" in events
    # test program is forward-only
    assert all(
        op.type != "sgd" for op in trainer.test_program.global_block().ops
    )


def test_trainer_resume_from_checkpoint(tmp_path):
    d = str(tmp_path / "ck")
    x, y, pred, loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    feed = _toy_feed(8)

    def reader():
        yield feed

    cc = pt.CheckpointConfig(d, epoch_interval=1)
    t1 = pt.Trainer(loss, checkpoint_config=cc)
    t1.train(reader, num_passes=3)
    assert t1.step == 3

    pt.reset_global_scope()
    x2 = _build_regression  # noqa: F841 (programs persist; scope was reset)
    t2 = pt.Trainer(loss, checkpoint_config=cc)
    t2.init()
    assert t2.start_pass == 3 and t2.step == 3


def test_save_inference_model_rejects_unused_feed(tmp_path):
    x, y, pred, loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    with pytest.raises(ValueError, match="bogus"):
        pt.io.save_inference_model(str(tmp_path / "m"), ["bogus"], [pred])


def test_shared_param_shape_conflict_rejected():
    x = pt.layers.data("ids", shape=[1], dtype=np.int64, lod_level=1)
    pt.layers.embedding(x, size=[100, 8], param_attr="shared_w")
    with pytest.raises(ValueError, match="shared_w"):
        pt.layers.embedding(x, size=[50, 16], param_attr="shared_w")


def test_trainer_midpass_resume(tmp_path, windowed):
    d = str(tmp_path / "ck")
    x, y, pred, loss = _build_regression()
    pt.optimizer.SGD(learning_rate=0.05).minimize(loss)
    feed = _toy_feed(40)

    def reader():
        for i in range(10):
            yield {"x": feed["x"][i * 4:(i + 1) * 4],
                   "y": feed["y"][i * 4:(i + 1) * 4]}

    # checkpoint every 3 steps; stop mid-pass after batch 5 (step 6)
    cc = pt.CheckpointConfig(d, epoch_interval=0, step_interval=3)
    t1 = pt.Trainer(loss, checkpoint_config=cc)

    def stop_at_6(e):
        if isinstance(e, pt.EndIteration) and e.step == 6:
            t1.stop()

    t1.train(reader, num_passes=2, event_handler=stop_at_6)

    # scan mode quantizes to window boundaries: the step-6 EndIteration
    # is delivered after its whole K=4 window (steps 5-8) trained, so
    # stop()/resume land at the window edge, not mid-window
    resume_at = 8 if windowed == "scan" else 6

    pt.reset_global_scope()
    t2 = pt.Trainer(loss, checkpoint_config=cc)
    t2.init()
    assert t2.start_pass == 0
    assert t2._resume_batch == resume_at and t2.step == resume_at
    seen = []
    t2.train(
        reader, num_passes=1,
        event_handler=lambda e: seen.append(e.batch_id)
        if isinstance(e, pt.EndIteration) else None,
    )
    # only the untrained tail of pass 0 ran
    assert seen == list(range(resume_at, 10))


def test_gradient_checker_fc_tanh():
    x = pt.layers.data("x", shape=[3])
    y = pt.layers.data("y", shape=[1])
    h = pt.layers.fc(x, size=5, act="tanh")
    pred = pt.layers.fc(h, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(1)
    feed = {"x": rng.randn(6, 3).astype(np.float32),
            "y": rng.randn(6, 1).astype(np.float32)}
    diffs = pt.check_gradient(loss, feed, eps=1e-2, rtol=5e-2, atol=1e-3)
    assert diffs


def test_gradient_checker_catches_wrong_grad(monkeypatch):
    """Sanity: the checker must FAIL when an op's math is wrong."""
    from paddle_tpu.core import registry

    x = pt.layers.data("x", shape=[3])
    h = pt.layers.fc(x, size=1)
    loss = pt.layers.mean(h)
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    orig = registry.get_kernel("mean")

    def bad_mean(ctx):
        import jax
        import jax.numpy as jnp
        xv = ctx.input("X")
        m = jnp.mean(xv)
        # value is 1.5*mean but jax.grad sees only 1.0*mean — the checker
        # must flag the analytic/numeric mismatch
        ctx.set_output("Out", m + 0.5 * jax.lax.stop_gradient(m))

    monkeypatch.setitem(registry._KERNELS, "mean", bad_mean)
    feed = {"x": np.random.RandomState(0).randn(4, 3).astype(np.float32)}
    with pytest.raises(AssertionError):
        pt.check_gradient(loss, feed, eps=1e-2, rtol=5e-2, atol=1e-3)
    monkeypatch.setitem(registry._KERNELS, "mean", orig)


def test_device_prefetcher_overlaps_and_preserves_order():
    """DataProvider double-buffer parity (DataProvider.h:375): batches come

    out in order, already on device, and the producer runs ahead."""
    import time

    import jax

    from paddle_tpu.data.feeder import DevicePrefetcher

    produced = []

    def reader():
        for i in range(5):
            produced.append(i)
            yield {"x": np.full((2, 2), i, np.float32)}

    got = []
    for feed in DevicePrefetcher(reader, depth=2):
        assert isinstance(feed["x"], jax.Array)
        got.append(int(np.asarray(feed["x"])[0, 0]))
        time.sleep(0.02)  # let the producer run ahead
    assert got == [0, 1, 2, 3, 4]
    assert produced == [0, 1, 2, 3, 4]


def test_device_prefetcher_propagates_reader_errors():
    from paddle_tpu.data.feeder import DevicePrefetcher

    def reader():
        yield {"x": np.zeros((1,), np.float32)}
        raise RuntimeError("reader exploded")

    it = iter(DevicePrefetcher(reader, depth=1))
    next(it)
    with pytest.raises(RuntimeError, match="reader exploded"):
        next(it)


def test_device_prefetcher_with_feeder_and_training():
    """End to end: prefetched feeds drive a training loop."""
    from paddle_tpu.data.feeder import DataFeeder, DevicePrefetcher

    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feeder = DataFeeder([x, y])
    rng = np.random.RandomState(0)

    def reader():
        for _ in range(6):
            yield [(rng.randn(4).astype(np.float32),
                    rng.randn(1).astype(np.float32)) for _ in range(8)]

    losses = []
    for _pass in range(3):
        for feed in DevicePrefetcher(reader, feeder, depth=2):
            (l,) = exe.run(feed=feed, fetch_list=[loss])
            losses.append(float(l))
    assert np.mean(losses[-6:]) < np.mean(losses[:6])


def test_trainer_prefetch_to_device(windowed):
    x = pt.layers.data("x", shape=[4])
    y = pt.layers.data("y", shape=[1])
    pred = pt.layers.fc(x, size=1)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.SGD(learning_rate=0.1).minimize(loss)
    trainer = pt.Trainer(cost=loss)
    rng = np.random.RandomState(1)

    def reader():
        for _ in range(4):
            yield [(rng.randn(4).astype(np.float32),
                    rng.randn(1).astype(np.float32)) for _ in range(8)]

    m = trainer.train(reader, num_passes=2, feed_order=[x, y],
                      prefetch_to_device=2)
    assert np.isfinite(m["cost"])
