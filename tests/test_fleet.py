"""Fleet e2e + replica-shutdown drain contracts (ISSUE 9).

Two layers:

- In-process: ModelRegistry.stop(drain_s=...) must let in-flight
  generation STREAMS finish (the replica half of graceful shutdown —
  before this PR only the trainer had a preemption drain contract),
  verified with artificially slowed pool steps so the stop provably
  lands mid-stream.

- Subprocess (`fleet` marker, time-bounded like test_chaos): real
  `python -m paddle_tpu serve` replicas behind the router.
  * SIGTERM mid-stream → the replica drains: the client's NDJSON
    stream ends in "done", never an error, and the process exits 0.
  * The chaos acceptance: SIGKILL one replica under load → the router
    trips that replica's breaker and fails requests over; a warmed
    standby is promoted; clients see ZERO non-retryable errors
    (200s throughout, or 503+Retry-After at worst), and after the
    probe admits the replacement the fleet serves clean.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.serving import ModelRegistry, Router, make_router_server
from paddle_tpu.serving.router import Fleet, ReplicaProcess, \
    replica_spawner

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

H, V, E = 8, 16, 6
BOS, EOS = 0, 1


def _build_gen_model(dirname: str, max_len: int = 64) -> None:
    """Tiny GRU-ish LM decoder (test_gen_serving's shape). Random
    weights rarely emit EOS, so decode runs ~max_len steps — long
    enough that a shutdown provably lands mid-stream."""
    pt.reset()
    pt.default_startup_program().random_seed = 3
    h0 = pt.layers.data("h0", shape=[-1, H], append_batch_size=False)
    gen = pt.layers.BeamSearchDecoder(beam_size=2, max_len=max_len,
                                      bos_id=BOS, eos_id=EOS)
    with gen.step():
        prev = gen.prev_ids()
        h_prev = gen.memory(init=h0)
        emb = pt.layers.embedding(prev, size=[V, E], param_attr="g_emb")
        h = pt.layers.fc(
            pt.layers.concat([emb, h_prev], axis=1), size=H, act="tanh",
            param_attr="g_w", bias_attr=pt.ParamAttr(name="g_b"))
        gen.update_memory(h_prev, h)
        gen.output_logits(pt.layers.fc(
            h, size=V, param_attr="g_wo",
            bias_attr=pt.ParamAttr(name="g_bo")))
    ids, scores, lengths = gen()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(dirname, ["h0"], [ids, scores, lengths])


def _build_dense_model(dirname: str) -> None:
    pt.reset()
    pt.default_startup_program().random_seed = 3
    x = pt.layers.data("x", shape=[4])
    h = pt.layers.fc(x, size=8, act="relu")
    pred = pt.layers.fc(h, size=1)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    pt.io.save_inference_model(dirname, ["x"], [pred])


@pytest.fixture(scope="module")
def gen_model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("fleet_gen"))
    _build_gen_model(d)
    return d


@pytest.fixture(scope="module")
def dense_model_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("fleet_dense"))
    _build_dense_model(d)
    return d


def _subprocess_env():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO_ROOT + (
        os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
    env.pop("PT_FLAGS_FAULT_SPEC", None)
    return env


# ----------------------------------------------- in-process drain -----------


def test_registry_stop_drains_inflight_generation_stream(gen_model_dir):
    """stop(drain_s) called MID-STREAM lets the stream finish: the
    client sees every token and a terminal done — never an error."""
    reg = ModelRegistry()
    engine, _ = reg.add("g", model_dir=gen_model_dir,
                        scheduler_kw=dict(max_slots=2, max_queue=4,
                                          timeout_ms=60000.0))
    reg.start()
    sched = engine.scheduler()
    orig = sched._step_once

    def slow_step():
        time.sleep(0.01)  # ~64 steps ⇒ the stream is up ~0.6s
        return orig()

    sched._step_once = slow_step
    h = sched.submit({"h0": np.zeros((1, H), np.float32)})
    events, done = [], threading.Event()

    def consume():
        for ev in h.events(timeout=60):
            events.append(ev)
        done.set()

    t = threading.Thread(target=consume, daemon=True)
    t.start()
    # wait until the stream is provably in flight (first token out)
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not any(
            e["event"] == "token" for e in events):
        time.sleep(0.005)
    assert any(e["event"] == "token" for e in events)
    t0 = time.monotonic()
    reg.stop(drain_s=30.0)
    assert done.wait(timeout=30)
    assert events[-1]["event"] == "done", events[-1]
    # the drain actually waited for the decode, not a no-op return
    assert time.monotonic() - t0 > 0.05


def test_registry_stop_without_drain_aborts_queued(gen_model_dir):
    """The contrast case: drain_s=0 (default) fails queued work with a
    RETRYABLE ShedError — a router would re-run it elsewhere."""
    reg = ModelRegistry()
    engine, _ = reg.add("g", model_dir=gen_model_dir,
                        scheduler_kw=dict(max_slots=1, max_queue=8,
                                          timeout_ms=60000.0))
    reg.start()
    sched = engine.scheduler()
    orig = sched._step_once

    def slow_step():
        time.sleep(0.01)
        return orig()

    sched._step_once = slow_step
    handles = [sched.submit({"h0": np.zeros((1, H), np.float32)})
               for _ in range(3)]
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline and not sched._active.any():
        time.sleep(0.005)
    reg.stop()
    kinds = set()
    for h in handles:
        for ev in h.events(timeout=30):
            pass
        kinds.add(ev["event"])
        if ev["event"] == "error":
            assert ev["kind"] in ("ShedError", "GenerationAborted"), ev
    assert "error" in kinds  # at least the queued ones were failed


# ----------------------------------------------- subprocess e2e -------------


def _post(url, path, payload, timeout=30):
    req = urllib.request.Request(
        url + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=timeout)


@pytest.mark.fleet
def test_replica_sigterm_drains_stream_then_exits_zero(gen_model_dir):
    """SIGTERM lands while an NDJSON generation stream is in flight:
    the `cli serve` handler drains — the stream ends with done, the
    process exits 0 (parity with the trainer's preemption drain)."""
    t_start = time.monotonic()
    proc = ReplicaProcess(
        ["--model_dir", gen_model_dir, "--gen_timeout_ms", "60000"],
        env=_subprocess_env())
    try:
        url = proc.wait_ready(timeout=180)
        resp = _post(url, "/generate",
                     {"inputs": {"h0": [[0.0] * H]}, "stream": True},
                     timeout=60)
        events = []
        line = resp.readline()  # first token: the stream is in flight
        events.append(json.loads(line))
        proc.terminate()  # SIGTERM mid-stream
        for line in resp:
            if line.strip():
                events.append(json.loads(line))
        assert events[0]["event"] == "token"
        assert events[-1]["event"] == "done", events[-1]
        assert all(e["event"] != "error" for e in events)
        assert proc.wait(timeout=60) == 0, proc.output_tail()
    finally:
        proc.kill()
    assert time.monotonic() - t_start < 300


@pytest.mark.fleet
@pytest.mark.chaos
def test_sigkill_under_load_fails_over_zero_nonretryable(dense_model_dir):
    """THE ISSUE 9 chaos acceptance. 2 replicas + 1 warm standby under
    client load; SIGKILL one replica. Required outcomes:
      - the router trips the killed replica's breaker,
      - in-flight/subsequent requests fail over (200) or surface as
        RETRYABLE 503s (Retry-After present) — zero non-retryable
        errors at any point,
      - the warm standby is promoted and, once probed up, the fleet
        serves clean again with no operator action."""
    t_start = time.monotonic()
    spawn = replica_spawner(
        ["--model_dir", dense_model_dir, "--max_batch_size", "8"],
        env=_subprocess_env())
    router = Router(probe_interval_s=0.1, probe_timeout_s=2.0,
                    request_timeout_s=20.0,
                    breaker_kw=dict(failure_threshold=2,
                                    reset_timeout_s=0.5))
    fleet = Fleet(spawn, replicas=2, standby=1, router=router,
                  supervise_interval_s=0.1)
    fleet.start()
    srv = make_router_server(router)
    srv.serve_background()
    url = f"http://127.0.0.1:{srv.port}"
    try:
        # warm standby must be parked before the kill
        deadline = time.monotonic() + 180
        while time.monotonic() < deadline \
                and fleet.warm.ready_count() < 1:
            time.sleep(0.1)
        assert fleet.warm.ready_count() >= 1

        outcomes = {"ok": 0, "retryable_503": 0, "non_retryable": []}
        stop = threading.Event()
        lock = threading.Lock()

        def client():
            payload = {"inputs": {"x": [[0.1, 0.2, 0.3, 0.4]]}}
            while not stop.is_set():
                try:
                    with _post(url, "/predict", payload) as r:
                        r.read()
                    with lock:
                        outcomes["ok"] += 1
                except urllib.error.HTTPError as e:
                    retryable = (e.code == 503
                                 and e.headers.get("Retry-After"))
                    with lock:
                        if retryable:
                            outcomes["retryable_503"] += 1
                        else:
                            outcomes["non_retryable"].append(
                                (e.code, e.read()[:200]))
                except Exception as e:  # noqa: BLE001
                    with lock:
                        outcomes["non_retryable"].append(repr(e))
                time.sleep(0.01)

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.0)  # load flowing on both replicas
        victim = router.replicas()[0]
        victim_name = victim.name
        victim.process.kill()
        # breaker trips (transport failures and/or supervisor trip)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline \
                and victim.breaker.state() != "open":
            time.sleep(0.02)
        assert victim.breaker.state() == "open"
        # replacement promoted from the warm pool and probed up
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            names = [r.name for r in router.replicas()]
            if victim_name not in names and len(names) == 2 and all(
                    r.up and r.breaker.state() == "closed"
                    for r in router.replicas()):
                break
            time.sleep(0.1)
        post_readmit_floor = None
        with lock:
            post_readmit_floor = outcomes["ok"]
        time.sleep(1.5)  # clean-serving window after re-admission
        stop.set()
        for t in threads:
            t.join(timeout=10)
        assert not outcomes["non_retryable"], outcomes
        assert outcomes["ok"] > post_readmit_floor, (
            "no successful traffic after the replacement was admitted")
        assert len(router.replicas()) == 2
        assert all(r.breaker.state() == "closed"
                   for r in router.replicas())
        assert fleet.replaced_total == 1
        # the victim served traffic pre-kill (counter survives its
        # removal from the rotation), and the promoted replica is
        # taking traffic now
        assert router.registry.counter_value(
            "pt_router_routed_total",
            labels={"replica": victim_name}) > 0
        routed = router.stats()["routed"]
        promoted = [r.name for r in router.replicas()
                    if r.name != victim_name]
        assert any(routed.get(n, 0) > 0 for n in promoted)
    finally:
        fleet.stop()
        srv.shutdown()
        srv.server_close()
    assert time.monotonic() - t_start < 300


@pytest.mark.fleet
def test_cli_serve_replicas_flag_e2e(dense_model_dir):
    """`cli serve --replicas 2` spawns the fleet and routes: requests
    land on both replicas and /healthz + /metrics answer fleet-wide.
    Exercises the CLI wiring itself (one spawn level deeper than the
    Fleet-object test above)."""
    import re
    import subprocess
    import sys

    t_start = time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu", "serve",
         "--model_dir", dense_model_dir, "--replicas", "2",
         "--port", "0", "--probe_interval_ms", "100"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        env=_subprocess_env(), text=True)
    url = None
    lines = []
    try:
        deadline = time.monotonic() + 240
        while time.monotonic() < deadline:
            line = proc.stdout.readline()
            if not line:
                break
            lines.append(line)
            m = re.search(r"routing .* on (http://[\w.\-]+:\d+)", line)
            if m:
                url = m.group(1)
                break
        assert url, "".join(lines)
        payload = {"inputs": {"x": [[0.1, 0.2, 0.3, 0.4]]}}
        for _ in range(8):
            with _post(url, "/predict", payload) as r:
                out = json.loads(r.read())
            assert "outputs" in out
        stats = json.loads(urllib.request.urlopen(
            url + "/stats", timeout=10).read())
        assert len(stats["replicas"]) == 2
        assert sum(stats["routed"].values()) == 8
        assert all(v > 0 for v in stats["routed"].values()), stats
        health = json.loads(urllib.request.urlopen(
            url + "/healthz", timeout=10).read())
        assert health["status"] == "ok"
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=60)
        except Exception:
            proc.kill()
    assert time.monotonic() - t_start < 300
