"""Sweeping finite-difference gradient checks over the layer library.

Reference: gserver/tests/test_LayerGrad.cpp — THE core correctness oracle:
every layer type gets its analytic gradients checked against central
differences. Each case builds a small net ending in a scalar loss and runs
pt.check_gradient over all trainable params.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.core.lod import LoDArray

def _rng():
    # fresh stream per case: inputs must not depend on test-run order
    return np.random.RandomState(0)


def _feed_dense(name, shape, dtype=np.float32, scale=0.5):
    rng = _rng()
    if np.issubdtype(np.dtype(dtype), np.integer):
        return {name: rng.randint(0, 4, shape).astype(dtype)}
    return {name: (rng.randn(*shape) * scale).astype(dtype)}


def _scalarize(v):
    return pt.layers.mean(pt.layers.elementwise_mul(v, v))


CASES = {}


def case(fn):
    CASES[fn.__name__[6:]] = fn
    return fn


@case
def build_fc_stack():
    x = pt.layers.data("x", shape=[6])
    h = pt.layers.fc(x, size=8, act="tanh")
    h = pt.layers.fc(h, size=5, act="sigmoid")
    return _scalarize(h), _feed_dense("x", (4, 6))


@case
def build_conv_pool_bn():
    x = pt.layers.data("x", shape=[2, 8, 8])
    h = pt.layers.conv2d(x, num_filters=3, filter_size=3, padding=1, act="relu")
    h = pt.layers.batch_norm(h)
    h = pt.layers.pool2d(h, pool_size=2, pool_type="avg")
    return _scalarize(h), _feed_dense("x", (2, 2, 8, 8))


@case
def build_conv_transpose():
    x = pt.layers.data("x", shape=[3, 5, 5])
    h = pt.layers.conv2d_transpose(x, num_filters=2, filter_size=3, stride=2,
                                   padding=1)
    return _scalarize(h), _feed_dense("x", (2, 3, 5, 5))


@case
def build_layer_norm():
    x = pt.layers.data("x", shape=[10])
    h = pt.layers.layer_norm(x)
    h = pt.layers.fc(h, size=4)
    return _scalarize(h), _feed_dense("x", (3, 10))


@case
def build_embedding_pool():
    ids = pt.layers.data("ids", shape=[-1], dtype=np.int32, lod_level=1,
                         append_batch_size=False)
    emb = pt.layers.embedding(ids, size=[12, 6])
    pooled = pt.layers.sequence_pool(emb, "average")
    return _scalarize(pooled), {
        "ids": LoDArray.from_sequences(
            [_rng().randint(0, 12, (3,)).astype(np.int32),
             _rng().randint(0, 12, (5,)).astype(np.int32)], bucket=16)
    }


@case
def build_lstm():
    x = pt.layers.data("x", shape=[-1, 16], lod_level=1,
                       append_batch_size=False)
    h = pt.layers.dynamic_lstm(x, size=16, max_len=8)
    last = pt.layers.sequence_last_step(h)
    return _scalarize(last), {
        "x": LoDArray.from_sequences(
            [_rng().randn(4, 16).astype(np.float32) * 0.3,
             _rng().randn(2, 16).astype(np.float32) * 0.3], bucket=16)
    }


@case
def build_gru():
    x = pt.layers.data("x", shape=[-1, 12], lod_level=1,
                       append_batch_size=False)
    h = pt.layers.dynamic_gru(x, size=4, max_len=8)
    return _scalarize(pt.layers.sequence_pool(h, "sum")), {
        "x": LoDArray.from_sequences(
            [_rng().randn(3, 12).astype(np.float32) * 0.3], bucket=8)
    }


@case
def build_sequence_conv():
    x = pt.layers.data("x", shape=[-1, 5], lod_level=1,
                       append_batch_size=False)
    h = pt.layers.sequence_conv(x, num_filters=4, filter_size=3)
    return _scalarize(pt.layers.sequence_pool(h, "max")), {
        "x": LoDArray.from_sequences(
            [_rng().randn(5, 5).astype(np.float32) * 0.5,
             _rng().randn(2, 5).astype(np.float32) * 0.5], bucket=16)
    }


@case
def build_nce_style_heads():
    x = pt.layers.data("x", shape=[7])
    h = pt.layers.fc(x, size=6, act="relu")
    a = pt.layers.fc(h, size=3)
    b = pt.layers.bilinear_tensor_product(h, h, size=2)
    return _scalarize(pt.layers.concat([a, b], axis=1)), _feed_dense("x", (3, 7))


@case
def build_recurrent_group():
    x = pt.layers.data("x", shape=[-1, 4], lod_level=1,
                       append_batch_size=False)
    rnn = pt.layers.RecurrentGroup(max_len=6)
    with rnn.step():
        x_t = rnn.step_input(x)
        h_prev = rnn.memory(shape=[5])
        h = pt.layers.fc(pt.layers.concat([x_t, h_prev], axis=1),
                         size=5, act="tanh")
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    out = rnn()
    return _scalarize(pt.layers.sequence_pool(out, "sum")), {
        "x": LoDArray.from_sequences(
            [_rng().randn(3, 4).astype(np.float32),
             _rng().randn(2, 4).astype(np.float32)], bucket=8)
    }


@pytest.mark.parametrize("name", sorted(CASES))
def test_layer_grad(name):
    pt.reset()
    pt.default_startup_program().random_seed = 3
    loss, feed = CASES[name]()
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    diffs = pt.check_gradient(loss, feed, eps=1e-2, rtol=5e-2, atol=2e-3)
    assert diffs, f"{name}: no parameters checked"
