"""Book 04: word2vec (N-gram language model) on imikolov.

Reference acceptance test: python/paddle/v2/fluid/tests/book/
test_word2vec.py — 4 context-word shared embeddings → fc → softmax over
the dictionary; train until the avg cost drops.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu.data import batch
from paddle_tpu.data.datasets import imikolov
from paddle_tpu.models import word2vec_net

N = 5  # n-gram


def test_word2vec():
    word_dict = imikolov.build_dict()
    dict_size = len(word_dict)

    words = [
        pt.layers.data(f"w{i}", shape=[1], dtype=np.int32) for i in range(N - 1)
    ]
    next_word = pt.layers.data("next", shape=[1], dtype=np.int32)
    logits = word2vec_net(words, dict_size, emb_dim=32)
    cost = pt.layers.mean(
        pt.layers.softmax_with_cross_entropy(logits, next_word)
    )
    pt.optimizer.Adam(learning_rate=1e-2).minimize(cost)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    reader = batch(imikolov.train(word_dict, N), 64, drop_last=True)
    first = last = None
    for _pass in range(4):
        for data in reader():
            arr = np.array(data, np.int32)
            feed = {f"w{i}": arr[:, i : i + 1] for i in range(N - 1)}
            feed["next"] = arr[:, N - 1 :]
            (last,) = exe.run(feed=feed, fetch_list=[cost])
            first = last if first is None else first
    assert float(last) < float(first) * 0.8, (first, last)
    # LM sanity: perplexity well below uniform
    assert float(last) < np.log(dict_size) * 0.9, (last, np.log(dict_size))
