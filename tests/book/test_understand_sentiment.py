"""Book 08: understand_sentiment — stacked LSTM on IMDB (ragged, no padding).

Reference acceptance test: python/paddle/v2/fluid/tests/book/
test_understand_sentiment_lstm.py / ..._stacked_lstm.py — embedding →
fc+lstm stack → pooled last states → softmax classifier, trained with Adam.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu.data import batch, shuffle
from paddle_tpu.data.datasets import imdb
from paddle_tpu.data.feeder import DataFeeder


def stacked_lstm_net(ids, vocab_size, emb_dim=32, hid_dim=32, stacked_num=2):
    """Reference: fluid tests book stacked_lstm_net."""
    emb = pt.layers.embedding(ids, size=[vocab_size, emb_dim])
    fc1 = pt.layers.fc(emb, size=hid_dim * 4)
    lstm1 = pt.layers.dynamic_lstm(fc1, size=hid_dim * 4, max_len=128)
    inputs = [fc1, lstm1]
    for _ in range(2, stacked_num + 1):
        fc = pt.layers.fc(inputs, size=hid_dim * 4)
        lstm = pt.layers.dynamic_lstm(fc, size=hid_dim * 4, is_reverse=False, max_len=128)
        inputs = [fc, lstm]
    fc_last = pt.layers.sequence_pool(inputs[0], "max")
    lstm_last = pt.layers.sequence_pool(inputs[1], "max")
    logits = pt.layers.fc([fc_last, lstm_last], size=2)
    return logits


def test_understand_sentiment_stacked_lstm():
    ids = pt.layers.data("words", shape=[-1], dtype=np.int32, lod_level=1,
                         append_batch_size=False)
    label = pt.layers.data("label", shape=[1], dtype=np.int32)
    logits = stacked_lstm_net(ids, vocab_size=5147)
    cost = pt.layers.softmax_with_cross_entropy(logits, label)
    avg_cost = pt.layers.mean(cost)
    acc = pt.layers.accuracy(logits, label)
    pt.optimizer.Adam(learning_rate=0.002).minimize(avg_cost)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    feeder = DataFeeder([ids, label], bucket=2048, max_seqs=16)
    reader = batch(shuffle(imdb.train(), 1000, seed=0), 16, drop_last=True)
    accs = []
    it = 0
    while it < 50:
        for data in reader():
            feed = feeder.feed(data)
            a, c = exe.run(feed=feed, fetch_list=[acc, avg_cost])
            accs.append(float(a))
            it += 1
            if it >= 50:
                break
    assert np.mean(accs[-10:]) > 0.8, f"final acc {np.mean(accs[-10:])}"
