"""Book 07: label_semantic_roles — SRL tagger with a linear-chain CRF.

Reference acceptance test: python/paddle/v2/fluid/tests/book/
test_label_semantic_roles.py — 8 feature embeddings → stacked bi-LSTM →
emissions → linear_chain_crf loss, crf_decoding for inference, chunk F1.
Here: the same feature set over the synthetic conll05 dataset, one
bi-GRU instead of the 8-layer stack (CI-sized), CRF loss + Viterbi +
ChunkEvaluator F1.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu.data import batch
from paddle_tpu.data.datasets import conll05
from paddle_tpu.data.feeder import DataFeeder
from paddle_tpu.evaluator import ChunkEvaluator

WORD_DIM = 16
HID = 32
MAX_LEN = 20


def db_lstm(feats, word_dict_len, pred_dict_len, label_dict_len):
    """Slimmed db_lstm (reference book 07): feature embeddings → fc →

    bi-GRU → emission fc."""
    word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred, mark = feats
    word_feats = [word, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2]
    embs = [
        pt.layers.embedding(w, size=[word_dict_len, WORD_DIM],
                            param_attr="srl_word_emb")
        for w in word_feats
    ]
    embs.append(pt.layers.embedding(pred, size=[pred_dict_len, WORD_DIM]))
    embs.append(pt.layers.embedding(mark, size=[2, WORD_DIM]))
    hidden = pt.layers.fc(embs, size=HID, act="tanh")
    fwd_in = pt.layers.fc(hidden, size=3 * HID, bias_attr=False)
    fwd = pt.layers.dynamic_gru(fwd_in, size=HID, max_len=MAX_LEN)
    bwd_in = pt.layers.fc(hidden, size=3 * HID, bias_attr=False)
    bwd = pt.layers.dynamic_gru(bwd_in, size=HID, is_reverse=True,
                                max_len=MAX_LEN)
    feat = pt.layers.sequence_concat([fwd, bwd])
    return pt.layers.fc(feat, size=label_dict_len)


def test_label_semantic_roles_crf():
    word_dict, verb_dict, label_dict = conll05.get_dict()
    n_labels = len(label_dict)

    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 5
    with pt.program_guard(prog, startup):
        names = ["word", "ctx_n2", "ctx_n1", "ctx_0", "ctx_p1", "ctx_p2",
                 "pred", "mark"]
        feats = [pt.layers.data(n, [-1], np.int32, lod_level=1,
                                append_batch_size=False) for n in names]
        label = pt.layers.data("label", [-1], np.int32, lod_level=1,
                               append_batch_size=False)
        emission = db_lstm(feats, len(word_dict), len(verb_dict), n_labels)
        crf_cost = pt.layers.linear_chain_crf(emission, label,
                                              param_attr="srl_crf_w",
                                              max_len=MAX_LEN)
        avg_cost = pt.layers.mean(crf_cost)
        decoded = pt.layers.crf_decoding(emission, param_attr="srl_crf_w",
                                         max_len=MAX_LEN)
        pt.optimizer.Adam(learning_rate=0.01).minimize(avg_cost)
    # evaluation must NOT run the optimizer slice — use the for-test clone
    # (reference: fluid Program.clone(for_test=True) in every book test)
    infer_prog = prog.clone(for_test=True)

    exe = pt.Executor()
    exe.run(startup)
    feeder = DataFeeder(feats + [label], bucket=512, max_seqs=16)
    reader = batch(conll05.train(), 16, drop_last=True)

    costs, it = [], 0
    while it < 320:
        for data in reader():
            feed = feeder.feed(data)
            (c,) = exe.run(prog, feed=feed, fetch_list=[avg_cost])
            costs.append(float(c))
            it += 1
            if it >= 320:
                break
    assert np.mean(costs[-5:]) < 0.5 * np.mean(costs[:5]), (
        f"CRF cost did not drop: {np.mean(costs[:5]):.2f} -> "
        f"{np.mean(costs[-5:]):.2f}"
    )

    # chunk F1 with Viterbi decode on held-out data
    chunk = ChunkEvaluator(num_chunk_types=4, chunk_scheme="iob")
    test_reader = batch(conll05.test(), 16, drop_last=True)
    n_batches = 0
    for data in test_reader():
        feed = feeder.feed(data)
        (dec,) = exe.run(infer_prog, feed=feed, fetch_list=[decoded],
                         return_numpy=False)
        pred = np.asarray(dec.data)[:, 0]
        offs = np.concatenate([[0], np.cumsum(np.asarray(dec.lengths))])
        preds = [pred[offs[i]:offs[i + 1]] for i in range(len(data))]
        labels = [np.asarray(row[-1]) for row in data]
        chunk.update(preds, labels)
        n_batches += 1
        if n_batches >= 4:
            break
    precision, recall, f1 = chunk.eval()
    assert f1 > 0.7, f"chunk F1 {f1:.3f} (p={precision:.3f} r={recall:.3f})"
