"""Book 05: recommender system on MovieLens.

Reference acceptance test: python/paddle/v2/fluid/tests/book/
test_recommender_system.py — dual-tower model: user features (id, gender,
age, job embeddings → fc) vs movie features (id embedding, sum-pooled
category embeddings, conv-pooled title sequence → fc), fused by cos_sim
scaled to the 5-point rating scale, square-error regression on the score.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu.core.lod import LoDArray
from paddle_tpu.data import batch, shuffle
from paddle_tpu.data.datasets import movielens

EMB = 16


def _user_tower():
    uid = pt.layers.data("uid", shape=[1], dtype=np.int32)
    gender = pt.layers.data("gender", shape=[1], dtype=np.int32)
    age = pt.layers.data("age", shape=[1], dtype=np.int32)
    job = pt.layers.data("job", shape=[1], dtype=np.int32)
    # the big id tables use is_sparse=True: SelectedRows row-wise grads +
    # lazy adam (reference book fixture also marks these IsSparse)
    feats = [
        pt.layers.embedding(uid, size=[movielens.max_user_id() + 1, EMB],
                            is_sparse=True),
        pt.layers.embedding(gender, size=[2, EMB // 2]),
        pt.layers.embedding(age, size=[len(movielens.age_table), EMB // 2]),
        pt.layers.embedding(job, size=[movielens.max_job_id() + 1, EMB // 2]),
    ]
    flat = [pt.layers.reshape(f, (-1, f.shape[-1])) for f in feats]
    return pt.layers.fc(pt.layers.concat(flat, axis=1), size=32, act="tanh")


def _movie_tower():
    mid = pt.layers.data("mid", shape=[1], dtype=np.int32)
    cats = pt.layers.data("cats", shape=[-1], dtype=np.int32, lod_level=1,
                          append_batch_size=False)
    title = pt.layers.data("title", shape=[-1], dtype=np.int32, lod_level=1,
                           append_batch_size=False)
    mid_emb = pt.layers.embedding(mid, size=[movielens.max_movie_id() + 1, EMB],
                                  is_sparse=True)
    mid_flat = pt.layers.reshape(mid_emb, (-1, EMB))
    cat_emb = pt.layers.embedding(
        cats, size=[len(movielens.movie_categories()), EMB // 2]
    )
    cat_pool = pt.layers.sequence_pool(cat_emb, "sum")
    title_emb = pt.layers.embedding(
        title, size=[len(movielens.get_movie_title_dict()), EMB],
        is_sparse=True,
    )
    title_pool = pt.layers.sequence_pool(title_emb, "average")
    return pt.layers.fc(
        pt.layers.concat([mid_flat, cat_pool, title_pool], axis=1),
        size=32,
        act="tanh",
    )


def test_recommender_system():
    usr = _user_tower()
    mov = _movie_tower()
    score = pt.layers.data("score", shape=[1])
    sim = pt.layers.cos_sim(usr, mov, scale=5.0)
    cost = pt.layers.mean(pt.layers.square_error_cost(sim, score))
    pt.optimizer.Adam(learning_rate=5e-3).minimize(cost)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    reader = batch(shuffle(movielens.train(), 512, seed=0), 32, drop_last=True)
    losses = []
    for _pass in range(3):
        for data in reader():
            n = len(data)
            feed = {
                "uid": np.array([[d[0]] for d in data], np.int32),
                "gender": np.array([[d[1]] for d in data], np.int32),
                "age": np.array([[d[2]] for d in data], np.int32),
                "job": np.array([[d[3]] for d in data], np.int32),
                "mid": np.array([[d[4]] for d in data], np.int32),
                "cats": LoDArray.from_sequences(
                    [np.array(d[5], np.int32) for d in data],
                    bucket=256, max_seqs=n),
                "title": LoDArray.from_sequences(
                    [np.array(d[6], np.int32) for d in data],
                    bucket=256, max_seqs=n),
                "score": np.array([[d[7]] for d in data], np.float32),
            }
            (l,) = exe.run(feed=feed, fetch_list=[cost])
            losses.append(float(l))
    k = max(1, len(losses) // 5)
    assert np.mean(losses[-k:]) < np.mean(losses[:k]) * 0.6, (
        np.mean(losses[:k]), np.mean(losses[-k:]))
