"""Book 01: linear regression on UCI housing.

Reference acceptance test: python/paddle/v2/fluid/tests/book/
test_fit_a_line.py — builds fc(1) + square_error_cost + SGD and asserts the
loss drops below 10 within the pass budget.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu.data import batch, shuffle
from paddle_tpu.data.datasets import uci_housing


def test_fit_a_line():
    x = pt.layers.data("x", shape=[13])
    y = pt.layers.data("y", shape=[1])
    y_predict = pt.layers.fc(x, size=1)
    cost = pt.layers.square_error_cost(y_predict, y)
    avg_cost = pt.layers.mean(cost)
    pt.optimizer.SGD(learning_rate=0.01).minimize(avg_cost)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    train_reader = batch(shuffle(uci_housing.train(), 500, seed=0), 20, drop_last=True)
    last = None
    for _pass in range(15):
        for data in train_reader():
            xs = np.stack([d[0] for d in data])
            ys = np.stack([d[1] for d in data])
            (last,) = exe.run(feed={"x": xs, "y": ys}, fetch_list=[avg_cost])
    assert last is not None and float(last) < 1.0, f"did not converge: {last}"
