"""Book 10: machine_translation — attention seq2seq + beam-search decode.

Reference acceptance test: python/paddle/v2/fluid/tests/book/
test_machine_translation.py (encoder-decoder with attention trained on
WMT16-style pairs) and the generation path of RecurrentGradientMachine
(beamSearch, RecurrentGradientMachine.h:309).

Uses a synthetic reversal task (target = reversed source) — the canonical
attention sanity check: the model must learn a content-dependent, position-
reversing alignment, which a no-attention encoder bottleneck gets wrong.
"""

import numpy as np

import paddle_tpu as pt
from paddle_tpu import models
from paddle_tpu.core.lod import LoDArray

BOS, EOS = 0, 1
VOCAB = 14
CAP = 128  # token capacity per batch side
NSEQ = 16


def make_batch(rng, n=NSEQ):
    srcs, trg_ins, labels = [], [], []
    for _ in range(n):
        L = rng.randint(3, 7)
        s = rng.randint(2, VOCAB, (L,)).astype(np.int32)
        t = s[::-1].copy()
        srcs.append(s)
        trg_ins.append(np.concatenate([[BOS], t]).astype(np.int32))
        labels.append(np.concatenate([t, [EOS]]).astype(np.int32))
    pack = lambda seqs: LoDArray.from_sequences(seqs, capacity=CAP, max_seqs=n)
    return pack(srcs), pack(trg_ins), pack(labels)


def build_train():
    src = pt.layers.data("src", shape=[-1], dtype=np.int32, lod_level=1,
                         append_batch_size=False)
    trg_in = pt.layers.data("trg_in", shape=[-1], dtype=np.int32, lod_level=1,
                            append_batch_size=False)
    label = pt.layers.data("label", shape=[-1], dtype=np.int32, lod_level=1,
                           append_batch_size=False)
    logits = models.seq2seq_attention(
        src, trg_in, src_vocab=VOCAB, trg_vocab=VOCAB,
        emb_dim=32, enc_hidden=32, dec_hidden=32,
        src_max_len=8, trg_max_len=8,
    )
    tok_loss = pt.layers.softmax_with_cross_entropy(logits, label)
    seq_loss = pt.layers.sequence_pool(tok_loss, "sum")
    cost = pt.layers.mean(seq_loss)
    pt.optimizer.Adam(learning_rate=0.005).minimize(cost)
    return cost


def test_machine_translation_train_and_beam_decode():
    rng = np.random.RandomState(7)
    train_prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 11  # deterministic parameter init
    with pt.program_guard(train_prog, startup):
        cost = build_train()
    exe = pt.Executor()
    exe.run(startup)

    costs = []
    for _ in range(400):
        src, trg_in, label = make_batch(rng)
        (c,) = exe.run(train_prog,
                       feed={"src": src, "trg_in": trg_in, "label": label},
                       fetch_list=[cost])
        costs.append(float(c))
    final = float(np.mean(costs[-10:]))
    assert final < 0.5, f"train cost did not converge: {final:.3f}"

    # ---- generation program shares weights by name; startup NOT run ----
    infer_prog = pt.Program()
    with pt.program_guard(infer_prog, pt.Program()):
        src_i = pt.layers.data("src", shape=[-1], dtype=np.int32, lod_level=1,
                               append_batch_size=False)
        ids_v, scores_v, lens_v = models.seq2seq_beam_decode(
            src_i, src_vocab=VOCAB, trg_vocab=VOCAB,
            emb_dim=32, enc_hidden=32, dec_hidden=32,
            beam_size=4, max_len=10, bos_id=BOS, eos_id=EOS, src_max_len=8,
        )
    src, _, _ = make_batch(rng, n=8)
    ids, scores, lens = exe.run(
        infer_prog, feed={"src": src}, fetch_list=[ids_v, scores_v, lens_v]
    )
    assert ids.shape == (8, 4, 10)
    # scores sorted best-first per batch row
    assert np.all(np.diff(scores, axis=1) <= 1e-5)

    srcs_np = np.asarray(src.data)
    lengths = np.asarray(src.lengths)
    offs = np.concatenate([[0], np.cumsum(lengths)])
    correct = 0
    for b in range(8):
        expect = srcs_np[offs[b]:offs[b + 1]][::-1]
        best = ids[b, 0, : lens[b, 0]]
        if best[-1] == EOS:
            best = best[:-1]
        if len(best) == len(expect) and np.all(best == expect):
            correct += 1
    assert correct >= 6, f"beam decode got {correct}/8 reversals right"
