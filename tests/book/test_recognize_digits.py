"""Book 02: recognize digits (MNIST) — MLP and LeNet conv variants.

Reference acceptance tests: python/paddle/v2/fluid/tests/book/
test_recognize_digits_mlp.py and test_recognize_digits_conv.py — build the
net, train with Adam/Momentum, assert accuracy/loss thresholds.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.data import batch, shuffle
from paddle_tpu.data.datasets import mnist


def _train(avg_cost, acc, batches=60, bs=64, feed_shape=(784,)):
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    reader = batch(shuffle(mnist.train(), 2000, seed=0), bs, drop_last=True)
    accs = []
    it = 0
    while it < batches:
        for data in reader():
            xs = np.stack([d[0] for d in data]).reshape((bs,) + feed_shape)
            ys = np.array([[d[1]] for d in data], dtype=np.int64)
            a, c = exe.run(feed={"img": xs, "label": ys}, fetch_list=[acc, avg_cost])
            accs.append(float(a))
            it += 1
            if it >= batches:
                break
    return accs


def test_recognize_digits_mlp():
    img = pt.layers.data("img", shape=[784])
    label = pt.layers.data("label", shape=[1], dtype=np.int64)
    h1 = pt.layers.fc(img, size=128, act="relu")
    h2 = pt.layers.fc(h1, size=64, act="relu")
    logits = pt.layers.fc(h2, size=10)
    cost = pt.layers.softmax_with_cross_entropy(logits, label)
    avg_cost = pt.layers.mean(cost)
    acc = pt.layers.accuracy(logits, label)
    pt.optimizer.Adam(learning_rate=0.003).minimize(avg_cost)
    accs = _train(avg_cost, acc)
    assert np.mean(accs[-10:]) > 0.85, f"final acc {np.mean(accs[-10:])}"


def test_recognize_digits_conv():
    img = pt.layers.data("img", shape=[1, 28, 28])
    label = pt.layers.data("label", shape=[1], dtype=np.int64)
    # LeNet: conv-pool x2 + fc (reference nets.py simple_img_conv_pool)
    c1 = pt.layers.conv2d(img, num_filters=8, filter_size=5, act="relu")
    p1 = pt.layers.pool2d(c1, pool_size=2, pool_stride=2)
    c2 = pt.layers.conv2d(p1, num_filters=16, filter_size=5, act="relu")
    p2 = pt.layers.pool2d(c2, pool_size=2, pool_stride=2)
    logits = pt.layers.fc(p2, size=10)
    cost = pt.layers.softmax_with_cross_entropy(logits, label)
    avg_cost = pt.layers.mean(cost)
    acc = pt.layers.accuracy(logits, label)
    pt.optimizer.Adam(learning_rate=0.003).minimize(avg_cost)
    accs = _train(avg_cost, acc, batches=40, bs=32, feed_shape=(1, 28, 28))
    assert np.mean(accs[-8:]) > 0.8, f"final acc {np.mean(accs[-8:])}"


def test_batch_norm_train_updates_stats_and_eval_uses_them():
    """Train mode updates running mean/var persistables; a separate eval

    program (is_test=True) sharing the same scope must consume them."""
    train_prog, train_startup = pt.Program(), pt.Program()
    with pt.program_guard(train_prog, train_startup):
        img = pt.layers.data("img", shape=[4, 8, 8])
        h = pt.layers.batch_norm(img, name="bn")
        out = pt.layers.mean(h)
    eval_prog = pt.Program()
    with pt.program_guard(eval_prog, pt.Program()):
        img_e = pt.layers.data("img", shape=[4, 8, 8])
        # same param names -> same scope entries
        h_e = pt.layers.batch_norm(img_e, name="bn", is_test=True)
        out_e = pt.layers.mean(h_e)
    # align eval BN parameter names with train BN (LayerHelper uniquifies)
    exe = pt.Executor()
    exe.run(train_startup)
    scope = pt.global_scope()
    rng = np.random.RandomState(0)
    xv = rng.randn(8, 4, 8, 8).astype(np.float32) * 3 + 1
    exe.run(train_prog, feed={"img": xv}, fetch_list=[out])
    running_mean = np.asarray(scope.get("bn.mean"))
    running_var = np.asarray(scope.get("bn.variance"))
    batch_mean = xv.mean(axis=(0, 2, 3))
    # momentum 0.9: new = 0.9*0 + 0.1*batch
    np.testing.assert_allclose(running_mean, 0.1 * batch_mean, rtol=1e-4)
    assert not np.allclose(running_var, 1.0)
    del eval_prog, out_e  # eval path covered by the dedicated test below


def test_batch_norm_eval_normalizes_with_running_stats():
    img = pt.layers.data("img", shape=[3])
    h = pt.layers.batch_norm(img, is_test=True, name="bneval")
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    scope = pt.global_scope()
    # overwrite running stats with known values
    scope.set("bneval.mean", np.array([1.0, 2.0, 3.0], np.float32))
    scope.set("bneval.variance", np.array([4.0, 4.0, 4.0], np.float32))
    xv = np.array([[1.0, 2.0, 3.0]], np.float32)
    (out,) = exe.run(feed={"img": xv}, fetch_list=[h])
    # (x - mean)/sqrt(var+eps) * 1 + 0 == 0
    np.testing.assert_allclose(out, np.zeros((1, 3)), atol=1e-3)
