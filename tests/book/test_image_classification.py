"""Book 03: image classification on CIFAR-10 (resnet_cifar10 + vgg).

Reference acceptance test: python/paddle/v2/fluid/tests/book/
test_image_classification_train.py — trains a small ResNet/VGG on cifar
and asserts the loss moves; here we also check train accuracy climbs above
chance on the synthetic cifar surrogate.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.data import batch, map_readers, shuffle
from paddle_tpu.data import image as pimg
from paddle_tpu.data.datasets import cifar
from paddle_tpu.models import resnet_cifar10, vgg


_AUG_COUNTER = [0]


def _augment(sample):
    """Reference training augmentation (v2/image.py simple_transform):
    resize short edge 36 → random 32-crop + mirror → CHW float.
    Deterministic but per-sample-varying seed (a per-class seed would
    freeze the transform for every image of that class)."""
    im, label = sample
    _AUG_COUNTER[0] += 1
    hwc = np.asarray(im, np.float32).reshape(3, 32, 32).transpose(1, 2, 0)
    out = pimg.simple_transform(hwc, resize_size=36, crop_size=32,
                                is_train=True,
                                rng=np.random.RandomState(_AUG_COUNTER[0]))
    return out, label


@pytest.mark.parametrize("net", ["resnet", "vgg"])
def test_image_classification_train(net):
    img = pt.layers.data("img", shape=[3, 32, 32])
    label = pt.layers.data("label", shape=[1], dtype=np.int32)
    if net == "resnet":
        logits = resnet_cifar10(img, class_dim=10, depth=20)
    else:
        logits = vgg(img, class_dim=10, depth=11)
    cost = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    acc = pt.layers.accuracy(pt.layers.softmax(logits), label)
    pt.optimizer.Adam(learning_rate=1e-3).minimize(cost)

    exe = pt.Executor()
    exe.run(pt.default_startup_program())

    reader = batch(
        map_readers(_augment, shuffle(cifar.train10(), 256, seed=0)),
        32, drop_last=True,
    )
    losses, accs = [], []
    max_steps = 25  # bound single-core CI runtime; convergence shows within this
    for _pass in range(3):
        for step, data in enumerate(reader()):
            if step >= max_steps:
                break
            xs = np.stack([d[0] for d in data]).reshape(-1, 3, 32, 32)
            ys = np.array([[d[1]] for d in data], np.int32)
            l, a = exe.run(feed={"img": xs, "label": ys}, fetch_list=[cost, acc])
            losses.append(float(l))
            accs.append(float(a))
    k = max(1, len(accs) // 4)
    assert np.mean(losses[-k:]) < np.mean(losses[:k]) * 0.9, (
        np.mean(losses[:k]), np.mean(losses[-k:]))
    assert np.mean(accs[-k:]) > 0.2, np.mean(accs[-k:])  # >2x chance
