"""Multi-process sharded checkpoint save → kill → restart → resume test.

Reference parity: go/pserver/service.go:346 (pserver checkpoint: each
server persists its own parameter blocks, trainers resume from the merged
state) and paddle/pserver/test/test_ParameterServer2.cpp (spawn real
processes, assert trained state survives). Here two localhost CPU
processes form a dp=2 mesh over the JAX coordinator, train with
ZeRO-sharded Adam state (each process owns half of every moment array),
save a sharded checkpoint where EACH PROCESS WRITES ONLY ITS OWN SHARDS,
die, and a fresh two-process job restores and trains on; the final
parameters must match an uninterrupted two-process run bit-for-bit.

The corruption paths (VERDICT r2 weak #5) are asserted in the parent:
a deleted shard file and a manifest missing a shard entry must both fail
loudly, never zero-fill.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_CHILD = r"""
import os, sys
import numpy as np
import jax
jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.environ["REPO"])
from paddle_tpu.parallel.distributed import init_distributed, is_chief

init_distributed()

import paddle_tpu as pt
from paddle_tpu import io as pio
from paddle_tpu import parallel as pp

MODE = os.environ["MODE"]          # full | part1 | part2
CKPT = os.environ["CKPT_DIR"]
OUT = os.environ["OUT_FILE"]


def build():
    x = pt.layers.data("x", shape=[16])
    y = pt.layers.data("y", shape=[1])
    h = pt.layers.fc(x, size=64, act="relu",
                     param_attr=pt.ParamAttr(name="w1"), bias_attr=False)
    pred = pt.layers.fc(h, size=1, param_attr=pt.ParamAttr(name="w2"),
                        bias_attr=False)
    loss = pt.layers.mean(pt.layers.square_error_cost(pred, y))
    pt.optimizer.Adam(learning_rate=0.05).minimize(loss)
    return loss


def feed(step):
    rng = np.random.RandomState(step)
    return {"x": rng.randn(16, 16).astype(np.float32),
            "y": rng.randn(16, 1).astype(np.float32)}


pt.default_main_program().random_seed = 3
pt.default_startup_program().random_seed = 3
loss = build()
prog = pt.default_main_program()
mesh = pp.make_mesh((2,), ("dp",))
exe = pp.ParallelExecutor(mesh, shard_optimizer_state=True)  # ZeRO-1
pt.Executor().run(pt.default_startup_program())


def train(steps, start=0):
    for s in range(start, start + steps):
        (l,) = exe.run(prog, feed=feed(s), fetch_list=[loss])
        assert np.isfinite(float(l)), l


if MODE == "full":
    train(6)
elif MODE == "part1":
    train(3)
    pio.save_sharded_checkpoint(CKPT, prog)
    # each process wrote ONLY its own shard file
    assert os.path.exists(os.path.join(CKPT, f"shards_p{jax.process_index()}.npz"))
elif MODE == "part2":
    restored = pio.load_sharded_checkpoint(CKPT, prog)
    assert "w1" in restored and "w2" in restored, restored
    train(3, start=3)
else:
    raise SystemExit(f"bad MODE {MODE}")

if MODE != "part1" and is_chief():
    from paddle_tpu.core.executor import global_scope
    np.savez(OUT, w1=np.asarray(global_scope().get("w1")),
             w2=np.asarray(global_scope().get("w2")))
print(f"proc {jax.process_index()} mode={MODE} ok", flush=True)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _run_job(mode, ckpt_dir, out_file, repo):
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.update(
            REPO=repo,
            MODE=mode,
            CKPT_DIR=ckpt_dir,
            OUT_FILE=out_file,
            COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            NUM_PROCESSES="2",
            PROCESS_ID=str(pid),
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=1",
        )
        env.pop("JAX_NUM_CPU_DEVICES", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", _CHILD], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=300)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        outs.append(out)
    for p, out in zip(procs, outs):
        assert p.returncode == 0, f"{mode} child failed:\n{out}"


@pytest.mark.needs_cpu_multiprocess
def test_two_process_sharded_checkpoint_resume(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    ckpt = str(tmp_path / "ckpt")
    ref_out = str(tmp_path / "ref.npz")
    res_out = str(tmp_path / "resumed.npz")

    _run_job("full", ckpt, ref_out, repo)       # uninterrupted oracle
    _run_job("part1", ckpt, "", repo)           # train 3, save, die
    _run_job("part2", ckpt, res_out, repo)      # restart, restore, train 3

    ref, res = np.load(ref_out), np.load(res_out)
    np.testing.assert_array_equal(ref["w1"], res["w1"])
    np.testing.assert_array_equal(ref["w2"], res["w2"])

    # the save must be genuinely distributed: both processes' shard files
    # referenced, and the ZeRO-sharded adam moments split across them
    with open(os.path.join(ckpt, "sharded_meta.json")) as f:
        meta = json.load(f)
    assert meta["num_processes"] == 2
    sharded = {n: v for n, v in meta["vars"].items() if v["kind"] == "sharded"}
    assert sharded, meta["vars"]
    owners = {e["process"] for v in sharded.values() for e in v["shards"]}
    assert owners == {0, 1}, owners

    # --- corruption paths: loud failure, never zero-fill ----------------
    from paddle_tpu import io as pio
    from paddle_tpu.core.executor import Scope

    # (a) manifest missing a shard entry (simulated partial write)
    broken = json.loads(json.dumps(meta))
    name = next(iter(sharded))
    broken["vars"][name]["shards"] = broken["vars"][name]["shards"][:1]
    with open(os.path.join(ckpt, "sharded_meta.json"), "w") as f:
        json.dump(broken, f)
    with pytest.raises(ValueError, match="uncovered"):
        pio.load_sharded_checkpoint(ckpt, scope=Scope())

    # (b) a deleted shard file
    with open(os.path.join(ckpt, "sharded_meta.json"), "w") as f:
        json.dump(meta, f)
    os.remove(os.path.join(ckpt, "shards_p1.npz"))
    with pytest.raises((FileNotFoundError, OSError)):
        pio.load_sharded_checkpoint(ckpt, scope=Scope())
