"""Model-zoo smoke tests: build + forward + one training step, finite loss.

Reference analogue: benchmark config parse tests and
gserver/tests/test_NetworkCompare.cpp (nets build and run). Spatial dims
are shrunk (96x96) to keep the 1-core CPU suite fast; architecture code
paths are identical.
"""

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import models
from paddle_tpu.core.lod import LoDArray


@pytest.mark.parametrize(
    "net,hw",
    [
        (models.alexnet, 96),
        (models.vgg, 96),
        (models.googlenet, 96),
        (models.resnet_imagenet, 96),
    ],
    ids=["alexnet", "vgg16", "googlenet", "resnet50"],
)
def test_imagenet_models_one_step(net, hw):
    img = pt.layers.data("img", shape=[3, hw, hw])
    label = pt.layers.data("label", shape=[1], dtype=np.int32)
    logits = net(img, class_dim=10)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.SGD(learning_rate=0.01).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.randn(2, 3, hw, hw).astype(np.float32)
    yv = rng.randint(0, 10, (2, 1)).astype(np.int32)
    (l,) = exe.run(feed={"img": xv, "label": yv}, fetch_list=[loss])
    assert np.isfinite(l), l


@pytest.mark.parametrize("net", [models.smallnet, models.lenet,
                                 models.resnet_cifar10],
                         ids=["smallnet", "lenet", "resnet32_cifar"])
def test_small_models_one_step(net):
    img = pt.layers.data("img", shape=[3, 32, 32])
    label = pt.layers.data("label", shape=[1], dtype=np.int32)
    logits = net(img, class_dim=10)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.Momentum(learning_rate=0.01).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    xv = rng.randn(4, 3, 32, 32).astype(np.float32)
    yv = rng.randint(0, 10, (4, 1)).astype(np.int32)
    (l,) = exe.run(feed={"img": xv, "label": yv}, fetch_list=[loss])
    assert np.isfinite(l), l


def test_lstm_benchmark_net_one_step():
    words = pt.layers.data("words", shape=[-1], dtype=np.int32, lod_level=1,
                           append_batch_size=False)
    label = pt.layers.data("label", shape=[1], dtype=np.int32)
    logits = models.lstm_benchmark_net(words, vocab_size=1000, emb_dim=16,
                                       hidden=16, max_len=16)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.Adam(0.002).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    seqs = [rng.randint(0, 1000, (int(rng.randint(3, 16)),)).astype(np.int32)
            for _ in range(4)]
    lod = LoDArray.from_sequences(seqs, capacity=64, max_seqs=4)
    yv = rng.randint(0, 2, (4, 1)).astype(np.int32)
    (l,) = exe.run(feed={"words": lod, "label": yv}, fetch_list=[loss])
    assert np.isfinite(l), l


def test_stacked_lstm_net_one_step():
    words = pt.layers.data("words", shape=[-1], dtype=np.int32, lod_level=1,
                           append_batch_size=False)
    label = pt.layers.data("label", shape=[1], dtype=np.int32)
    logits = models.stacked_lstm_net(words, vocab_size=500, emb_dim=8,
                                     hid_dim=8, stacked_num=3, max_len=16)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, label))
    pt.optimizer.Adam(0.002).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    seqs = [rng.randint(0, 500, (int(rng.randint(3, 16)),)).astype(np.int32)
            for _ in range(4)]
    lod = LoDArray.from_sequences(seqs, capacity=64, max_seqs=4)
    yv = rng.randint(0, 2, (4, 1)).astype(np.int32)
    (l,) = exe.run(feed={"words": lod, "label": yv}, fetch_list=[loss])
    assert np.isfinite(l), l


def test_word2vec_net_one_step():
    ws = [pt.layers.data(f"w{i}", shape=[1], dtype=np.int32) for i in range(4)]
    nxt = pt.layers.data("next", shape=[1], dtype=np.int32)
    logits = models.word2vec_net(ws, dict_size=100, emb_dim=8)
    loss = pt.layers.mean(pt.layers.softmax_with_cross_entropy(logits, nxt))
    pt.optimizer.SGD(0.1).minimize(loss)
    exe = pt.Executor()
    exe.run(pt.default_startup_program())
    rng = np.random.RandomState(0)
    feed = {f"w{i}": rng.randint(0, 100, (8, 1)).astype(np.int32) for i in range(4)}
    feed["next"] = rng.randint(0, 100, (8, 1)).astype(np.int32)
    (l,) = exe.run(feed=feed, fetch_list=[loss])
    assert np.isfinite(l)
